"""Integration scenarios closing VERDICT r1's coverage gaps vs the
reference's 44-entry envtest suite
(test/integration/controller/jobset_controller_test.go:208-1663):

* custom-subdomain DNS shapes (pod FQDNs, coordinator endpoint),
* coordinator label AND annotation on EVERY child object
  (jobset_controller.go:745-749),
* TTL-after-finished interacting with gang restarts,
* nodeSelector placement strategy end-to-end through the `label-nodes`
  CLI against a running controller server.
"""

from __future__ import annotations

import pytest

from jobset_tpu.api import FailurePolicy, keys
from jobset_tpu.api.types import Coordinator, Network
from jobset_tpu.core import make_cluster
from jobset_tpu.testing import make_jobset, make_replicated_job

TOPOLOGY = "cloud.google.com/gke-nodepool"


def _jobset(name="js", replicas=2, pods=2):
    return (
        make_jobset(name)
        .replicated_job(
            make_replicated_job("workers")
            .replicas(replicas)
            .parallelism(pods)
            .completions(pods)
            .obj()
        )
        .obj()
    )


# ---------------------------------------------------------------------------
# Custom-subdomain DNS shapes (jobset_types.go:230-247; e2e_test.go:64-110)
# ---------------------------------------------------------------------------


def test_custom_subdomain_shapes_pod_fqdns_and_coordinator():
    cluster = make_cluster()
    cluster.add_topology(TOPOLOGY, num_domains=4, nodes_per_domain=2)
    js = _jobset("trainer")
    js.spec.network = Network(enable_dns_hostnames=True, subdomain="mesh-net")
    js.spec.coordinator = Coordinator(
        replicated_job="workers", job_index=0, pod_index=0
    )
    cluster.create_jobset(js)
    cluster.run_until_stable()

    # Service named after the custom subdomain, not the JobSet.
    assert ("default", "mesh-net") in cluster.services
    assert ("default", "trainer") not in cluster.services

    # Pod hostname contract resolves through the custom subdomain.
    pod = cluster.resolve_hostname("default", "trainer-workers-1-1.mesh-net")
    assert pod is not None
    assert pod.spec.subdomain == "mesh-net"

    # Coordinator endpoint = <pod>.<custom-subdomain> on every child.
    endpoint = "trainer-workers-0-0.mesh-net"
    for job in cluster.jobs.values():
        assert job.labels[keys.COORDINATOR_KEY] == endpoint
        assert job.metadata.annotations[keys.COORDINATOR_KEY] == endpoint
    for pod in cluster.pods.values():
        assert pod.labels[keys.COORDINATOR_KEY] == endpoint
        assert pod.annotations[keys.COORDINATOR_KEY] == endpoint


def test_coordinator_label_and_annotation_on_every_child_object():
    """jobset_controller.go:745-749 stamps BOTH the label and annotation on
    every job and every pod — not just the coordinator's own."""
    cluster = make_cluster()
    js = (
        make_jobset("js")
        .replicated_job(
            make_replicated_job("leader").replicas(1).parallelism(1).completions(1).obj()
        )
        .replicated_job(
            make_replicated_job("workers").replicas(3).parallelism(2).completions(2).obj()
        )
        .obj()
    )
    js.spec.coordinator = Coordinator(replicated_job="leader", job_index=0, pod_index=0)
    cluster.create_jobset(js)
    cluster.run_until_stable()

    endpoint = "js-leader-0-0.js"
    assert len(cluster.jobs) == 4
    assert len(cluster.pods) == 7
    for job in cluster.jobs.values():
        assert job.labels.get(keys.COORDINATOR_KEY) == endpoint, job.metadata.name
        assert job.metadata.annotations.get(keys.COORDINATOR_KEY) == endpoint
        # and on the pod template, so recreated pods inherit it
        assert job.spec.template.labels.get(keys.COORDINATOR_KEY) == endpoint
    for pod in cluster.pods.values():
        assert pod.labels.get(keys.COORDINATOR_KEY) == endpoint, pod.metadata.name
        assert pod.annotations.get(keys.COORDINATOR_KEY) == endpoint


# ---------------------------------------------------------------------------
# TTL x restart interplay (ttl_after_finished.go + failure_policy.go)
# ---------------------------------------------------------------------------


def test_ttl_counts_from_finish_after_gang_restarts():
    cluster = make_cluster()
    js = _jobset("restarty")
    js.spec.failure_policy = FailurePolicy(max_restarts=2)
    js.spec.ttl_seconds_after_finished = 60
    cluster.create_jobset(js)
    cluster.run_until_stable()

    # Two gang restarts; the TTL clock must not start from either failure.
    for _ in range(2):
        cluster.fail_job("default", "restarty-workers-0")
        cluster.run_until_stable()
    stored = cluster.get_jobset("default", "restarty")
    assert stored.status.restarts == 2

    cluster.clock.advance(120)  # long-dead time BEFORE finishing
    cluster.run_until_stable()
    assert cluster.get_jobset("default", "restarty") is not None

    cluster.complete_all_jobs(stored)
    cluster.run_until_stable()
    assert cluster.jobset_has_condition(
        cluster.get_jobset("default", "restarty"), "Completed"
    )

    cluster.clock.advance(59)
    cluster.run_until_stable()
    assert cluster.get_jobset("default", "restarty") is not None  # not yet

    cluster.clock.advance(2)
    cluster.run_until_stable()
    assert cluster.get_jobset("default", "restarty") is None  # TTL from finish


def test_ttl_cleans_up_jobset_failed_after_max_restarts():
    cluster = make_cluster()
    js = _jobset("doomed")
    js.spec.failure_policy = FailurePolicy(max_restarts=1)
    js.spec.ttl_seconds_after_finished = 30
    cluster.create_jobset(js)
    cluster.run_until_stable()

    cluster.fail_job("default", "doomed-workers-0")
    cluster.run_until_stable()
    cluster.fail_job("default", "doomed-workers-0")
    cluster.run_until_stable()
    stored = cluster.get_jobset("default", "doomed")
    assert cluster.jobset_has_condition(stored, "Failed")
    # Failed terminally: active jobs were torn down, TTL armed.
    cluster.clock.advance(31)
    cluster.run_until_stable()
    assert cluster.get_jobset("default", "doomed") is None


def test_restart_attempt_labels_reset_ttl_irrelevant_children():
    """After a restart, only current-attempt children exist; the stale
    attempt's jobs are deleted (not TTL'd) — restart-attempt bucketing
    (jobset_controller.go:279-290)."""
    cluster = make_cluster()
    js = _jobset("attempts")
    js.spec.failure_policy = FailurePolicy(max_restarts=3)
    cluster.create_jobset(js)
    cluster.run_until_stable()
    cluster.fail_job("default", "attempts-workers-1")
    cluster.run_until_stable()

    jobs = list(cluster.jobs.values())
    assert len(jobs) == 2
    assert all(j.labels[keys.RESTARTS_KEY] == "1" for j in jobs)


# ---------------------------------------------------------------------------
# Lifecycle scenarios mirroring remaining reference envtest entries
# (test/integration/controller/jobset_controller_test.go)
# ---------------------------------------------------------------------------


def test_headless_service_recreated_if_deleted():
    """Reference entry "service deleted" (jobset_controller_test.go:999):
    the reconciler recreates the headless service on its next pass."""
    cluster = make_cluster()
    js = _jobset("svc-js")
    cluster.create_jobset(js)
    cluster.run_until_stable()
    assert ("default", "svc-js") in cluster.services

    del cluster.services[("default", "svc-js")]
    cluster.enqueue_reconcile("default", "svc-js")
    cluster.run_until_stable()
    assert ("default", "svc-js") in cluster.services


def test_jobset_succeeds_after_one_failure():
    """Reference entry "job succeeds after one failure"
    (jobset_controller_test.go:856): a gang restart is not terminal — the
    recreated attempt can complete the JobSet, with restarts recorded."""
    cluster = make_cluster()
    js = _jobset("phoenix")
    js.spec.failure_policy = FailurePolicy(max_restarts=2)
    cluster.create_jobset(js)
    cluster.run_until_stable()

    cluster.fail_job("default", "phoenix-workers-0")
    cluster.run_until_stable()
    stored = cluster.get_jobset("default", "phoenix")
    assert stored.status.restarts == 1
    assert not cluster.jobset_has_condition(stored, "Failed")

    cluster.complete_all_jobs(stored)
    cluster.run_until_stable()
    stored = cluster.get_jobset("default", "phoenix")
    assert cluster.jobset_has_condition(stored, "Completed")
    assert stored.status.restarts == 1


def test_failed_jobset_deletes_active_jobs():
    """Reference entry "active jobs are deleted after jobset fails"
    (jobset_controller_test.go:1093)."""
    cluster = make_cluster()
    js = _jobset("halfdead", replicas=3)
    cluster.create_jobset(js)  # no failure policy: first failure is terminal
    cluster.run_until_stable()
    assert len(cluster.jobs) == 3

    cluster.fail_job("default", "halfdead-workers-1")
    cluster.run_until_stable()
    stored = cluster.get_jobset("default", "halfdead")
    assert cluster.jobset_has_condition(stored, "Failed")
    # The failed job object remains (evidence); the still-active siblings
    # are torn down (jobset_controller.go:156-160).
    remaining = [j.metadata.name for j in cluster.jobs.values()]
    assert remaining == ["halfdead-workers-1"]
    assert all(p.status.phase == "Failed" for p in cluster.pods.values())


def test_success_policy_all_with_empty_target_list_targets_every_rjob():
    """Reference entry "success policy 'all' with empty replicated jobs
    list" (jobset_controller_test.go:260): no targets = all replicated
    jobs must succeed."""
    from jobset_tpu.api import SuccessPolicy

    cluster = make_cluster()
    js = (
        make_jobset("allof")
        .success_policy(SuccessPolicy(operator=keys.OPERATOR_ALL))
        .replicated_job(
            make_replicated_job("a").replicas(1).parallelism(1).completions(1).obj()
        )
        .replicated_job(
            make_replicated_job("b").replicas(2).parallelism(1).completions(1).obj()
        )
        .obj()
    )
    cluster.create_jobset(js)
    cluster.run_until_stable()

    cluster.complete_job("default", "allof-a-0")
    cluster.complete_job("default", "allof-b-0")
    cluster.run_until_stable()
    stored = cluster.get_jobset("default", "allof")
    assert not cluster.jobset_has_condition(stored, "Completed")  # b-1 open

    cluster.complete_job("default", "allof-b-1")
    cluster.run_until_stable()
    assert cluster.jobset_has_condition(
        cluster.get_jobset("default", "allof"), "Completed"
    )


def test_generate_name_jobset_gets_service_named_after_generated_name():
    """Reference entry "jobset using generateName with enableDNSHostnames
    should have headless service name set to the jobset name"
    (jobset_controller_test.go:1119): the apiserver-analog generates the
    name at admission; the headless service follows the generated name."""
    cluster = make_cluster()
    js = _jobset("ignored")
    js.metadata.name = ""
    js.metadata.generate_name = "gen-"
    created = cluster.create_jobset(js)
    assert created.metadata.name.startswith("gen-")
    assert len(created.metadata.name) > len("gen-")
    cluster.run_until_stable()

    # Default subdomain (and so the service) = the generated jobset name.
    assert ("default", created.metadata.name) in cluster.services
    pod = next(iter(cluster.pods.values()))
    assert pod.spec.subdomain == created.metadata.name
    # Round-trips through the wire format.
    from jobset_tpu import api

    again = api.from_dict(api.to_dict(created))
    assert again.metadata.name == created.metadata.name


def test_in_order_startup_reapplied_after_gang_restart():
    """Reference entry "startupPolicy with InOrder; success policy restart"
    (jobset_controller_test.go:1408): after a gang restart the InOrder gate
    applies to the NEW attempt — workers wait for the recreated driver."""
    from jobset_tpu.api import StartupPolicy

    cluster = make_cluster(auto_ready=False)
    js = (
        make_jobset("ordered")
        .startup_policy(StartupPolicy(startup_policy_order=keys.STARTUP_IN_ORDER))
        .failure_policy(FailurePolicy(max_restarts=2))
        .replicated_job(
            make_replicated_job("driver").replicas(1).parallelism(1).completions(1).obj()
        )
        .replicated_job(
            make_replicated_job("workers").replicas(2).parallelism(1).completions(1).obj()
        )
        .obj()
    )
    cluster.create_jobset(js)
    cluster.run_until_stable()
    cluster.set_job_ready("default", "ordered-driver-0")
    cluster.run_until_stable()
    assert len(cluster.jobs) == 3  # driver started -> workers created

    cluster.fail_job("default", "ordered-workers-1")
    cluster.run_until_stable()
    stored = cluster.get_jobset("default", "ordered")
    assert stored.status.restarts == 1
    # New attempt: only the driver exists until it reports ready again.
    names = sorted(j.metadata.name for j in cluster.jobs.values())
    assert names == ["ordered-driver-0"]
    assert all(
        j.labels[keys.RESTARTS_KEY] == "1" for j in cluster.jobs.values()
    )

    cluster.set_job_ready("default", "ordered-driver-0")
    cluster.run_until_stable()
    assert sorted(j.metadata.name for j in cluster.jobs.values()) == [
        "ordered-driver-0",
        "ordered-workers-0",
        "ordered-workers-1",
    ]


# ---------------------------------------------------------------------------
# nodeSelector strategy end-to-end with the label-nodes tool
# (hack/label_nodes/label_nodes.py + jobset_controller.go:674-696)
# ---------------------------------------------------------------------------


@pytest.fixture()
def server():
    from jobset_tpu.server import ControllerServer

    s = ControllerServer("127.0.0.1:0", tick_interval=0.05).start()
    yield s
    s.stop()


def test_node_selector_strategy_e2e_through_label_nodes_cli(server, tmp_path):
    from jobset_tpu.cli import main as cli_main
    from jobset_tpu.client import JobSetClient

    client = JobSetClient(server.address)
    # A two-nodepool topology, as a GKE admin would have it.
    for pool, n in (("pool-a", 2), ("pool-b", 2)):
        for i in range(n):
            client.create_node(
                f"{pool}-node-{i}", labels={TOPOLOGY: pool}, capacity=8
            )

    # Pre-label both pools for jobset "strategy/js": job 0 -> pool-a, 1 -> b.
    rc = cli_main([
        "label-nodes",
        "--server", server.address,
        "--topology-key", TOPOLOGY,
        "--jobset", "js", "--namespace", "strategy",
        "--replicated-job", "workers",
    ])
    assert rc == 0

    nodes = {n["metadata"]["name"]: n for n in client.nodes()}
    assert (
        nodes["pool-a-node-0"]["metadata"]["labels"][keys.NAMESPACED_JOB_KEY]
        == "strategy_js-workers-0"
    )
    assert (
        nodes["pool-b-node-1"]["metadata"]["labels"][keys.NAMESPACED_JOB_KEY]
        == "strategy_js-workers-1"
    )

    manifest = f"""
apiVersion: jobset.x-k8s.io/v1alpha2
kind: JobSet
metadata:
  name: js
  namespace: strategy
  annotations:
    alpha.jobset.sigs.k8s.io/exclusive-topology: {TOPOLOGY}
    alpha.jobset.sigs.k8s.io/node-selector: "true"
spec:
  replicatedJobs:
  - name: workers
    replicas: 2
    template:
      spec:
        parallelism: 2
        completions: 2
        template:
          spec:
            containers:
            - name: t
              image: t:latest
"""
    client.create(manifest, namespace="strategy")

    import time

    deadline = time.monotonic() + 20
    while time.monotonic() < deadline:
        pods = client.pods(namespace="strategy")
        if len(pods) == 4 and all(p["spec"]["nodeName"] for p in pods):
            break
        time.sleep(0.1)
    else:
        raise AssertionError(f"pods unbound: {client.pods(namespace='strategy')}")

    # Strategy pods carry the namespaced-job nodeSelector + toleration, and
    # each job's pods landed wholly inside its labelled pool.
    by_job: dict[str, set[str]] = {}
    for p in pods:
        selector = p["spec"]["nodeSelector"]
        assert selector.get(keys.NAMESPACED_JOB_KEY, "").startswith(
            "strategy_js-workers-"
        ), selector
        pool = nodes[p["spec"]["nodeName"]]["metadata"]["labels"][TOPOLOGY]
        by_job.setdefault(p["metadata"]["labels"][keys.JOB_INDEX_KEY], set()).add(pool)
    assert by_job == {"0": {"pool-a"}, "1": {"pool-b"}}


# ---------------------------------------------------------------------------
# activeDeadlineSeconds -> DeadlineExceeded feeding failure-policy rules
# (k8s Job semantics; failure_policy.go OnJobFailureReasons matching)


def _deadline_jobset(name, rules=None, max_restarts=0):
    b = make_jobset(name).failure_policy(
        FailurePolicy(max_restarts=max_restarts, rules=rules or [])
    )
    rjob = make_replicated_job("workers").replicas(1).parallelism(1).obj()
    rjob.template.spec.active_deadline_seconds = 30
    return b.replicated_job(rjob).obj()


def test_active_deadline_fails_job_with_deadline_exceeded():
    """A running job whose activeDeadlineSeconds passes on the virtual
    clock fails with the DeadlineExceeded reason — organically, not via
    test injection — and the JobSet (no matching rule, maxRestarts=0)
    fails."""
    cluster = make_cluster()
    cluster.add_topology(TOPOLOGY, num_domains=2, nodes_per_domain=2, capacity=4)
    cluster.create_jobset(_deadline_jobset("dl"))
    cluster.run_until_stable()
    assert not cluster.get_jobset("default", "dl").status.terminal_state

    cluster.clock.advance(29)
    cluster.run_until_stable()
    assert not cluster.get_jobset("default", "dl").status.terminal_state

    cluster.clock.advance(2)  # past the 30s deadline
    cluster.run_until_stable()
    live = cluster.get_jobset("default", "dl")
    assert live.status.terminal_state == keys.JOBSET_FAILED
    job_conds = [
        c for j in cluster.jobs_for_jobset(live) for c in j.status.conditions
    ]
    assert any(
        c.reason == keys.JOB_REASON_DEADLINE_EXCEEDED for c in job_conds
    )


def test_failure_rule_matches_organic_deadline_exceeded():
    """A RestartJobSet rule targeting OnJobFailureReasons=[DeadlineExceeded]
    matches the organically-produced reason and gang-restarts instead of
    failing."""
    from jobset_tpu.api.types import FailurePolicyRule

    rule = FailurePolicyRule(
        name="restartOnDeadline",
        action="RestartJobSet",
        on_job_failure_reasons=[keys.JOB_REASON_DEADLINE_EXCEEDED],
    )
    cluster = make_cluster()
    cluster.add_topology(TOPOLOGY, num_domains=2, nodes_per_domain=2, capacity=4)
    cluster.create_jobset(_deadline_jobset("dl-r", rules=[rule], max_restarts=3))
    cluster.run_until_stable()

    cluster.clock.advance(31)
    cluster.run_until_stable()
    live = cluster.get_jobset("default", "dl-r")
    assert not live.status.terminal_state
    assert live.status.restarts == 1  # gang-restarted by the matching rule


def test_suspended_job_does_not_enforce_deadline():
    """k8s semantics: suspension pauses the deadline; resume re-arms it
    from the fresh start time."""
    cluster = make_cluster()
    cluster.add_topology(TOPOLOGY, num_domains=2, nodes_per_domain=2, capacity=4)
    cluster.create_jobset(_deadline_jobset("dl-s"))
    cluster.run_until_stable()

    live = cluster.get_jobset("default", "dl-s")
    live.spec.suspend = True
    cluster.update_jobset(live)
    cluster.run_until_stable()

    cluster.clock.advance(120)  # way past the 30s deadline, while suspended
    cluster.run_until_stable()
    assert not cluster.get_jobset("default", "dl-s").status.terminal_state

    live = cluster.get_jobset("default", "dl-s")
    live.spec.suspend = False
    cluster.update_jobset(live)
    cluster.run_until_stable()
    cluster.clock.advance(31)  # new deadline counted from the resume
    cluster.run_until_stable()
    assert (
        cluster.get_jobset("default", "dl-s").status.terminal_state
        == keys.JOBSET_FAILED
    )


# ---------------------------------------------------------------------------
# Remaining envtest-scenario parity (jobset_controller_test.go:292-1663):
# success-policy matrix corners, rules-order 1 and 3, replicatedJobsStatuses
# after success, and the managedBy contract incl. the status subresource.
# ---------------------------------------------------------------------------


def _two_rjob_cluster(js_name="js", success_policy=None):
    from jobset_tpu.api import SuccessPolicy  # noqa: F401 (callers build it)

    cluster = make_cluster()
    cluster.add_topology("rack", num_domains=8, nodes_per_domain=4, capacity=16)
    js = (
        make_jobset(js_name)
        .replicated_job(
            make_replicated_job("a").replicas(2).parallelism(1).completions(1).obj()
        )
        .replicated_job(
            make_replicated_job("b").replicas(3).parallelism(1).completions(1).obj()
        )
    )
    js = js.obj()
    if success_policy is not None:
        js.spec.success_policy = success_policy
    cluster.create_jobset(js)
    cluster.run_until_stable()
    return cluster, js


def test_success_policy_all_with_targets_ignores_other_rjobs():
    """'all' with TargetReplicatedJobs (jobset_controller_test.go:292):
    completing every job of a NON-targeted rjob keeps the jobset active;
    only the targeted rjob's full completion completes it."""
    from jobset_tpu.api import SuccessPolicy

    cluster, js = _two_rjob_cluster(
        "all-b",
        SuccessPolicy(operator=keys.OPERATOR_ALL, target_replicated_jobs=["b"]),
    )
    for i in range(2):  # all of rjob a — not targeted
        cluster.complete_job("default", f"all-b-a-{i}")
    cluster.run_until_stable()
    assert js.status.terminal_state == ""
    cluster.complete_job("default", "all-b-b-0")
    cluster.run_until_stable()
    assert js.status.terminal_state == ""  # 1 of 3 targeted
    for i in (1, 2):
        cluster.complete_job("default", f"all-b-b-{i}")
    cluster.run_until_stable()
    assert js.status.terminal_state == keys.JOBSET_COMPLETED


def test_success_policy_any_untargeted_completes_on_first_success():
    """'any' with empty targets (jobset_controller_test.go:357): any one
    job completing completes the whole jobset."""
    from jobset_tpu.api import SuccessPolicy

    cluster, js = _two_rjob_cluster(
        "any-all",
        SuccessPolicy(operator=keys.OPERATOR_ANY, target_replicated_jobs=[]),
    )
    cluster.complete_job("default", "any-all-b-1")
    cluster.run_until_stable()
    assert js.status.terminal_state == keys.JOBSET_COMPLETED


def _rules_jobset(name, rules, max_restarts=1):
    from jobset_tpu.api import FailurePolicyRule  # noqa: F401

    cluster = make_cluster()
    cluster.add_topology("rack", num_domains=8, nodes_per_domain=4, capacity=16)
    js = (
        make_jobset(name)
        .failure_policy(FailurePolicy(max_restarts=max_restarts, rules=rules))
        .replicated_job(
            make_replicated_job("a").replicas(2).parallelism(1).completions(1).obj()
        )
        .replicated_job(
            make_replicated_job("b").replicas(1).parallelism(1).completions(1).obj()
        )
        .obj()
    )
    cluster.create_jobset(js)
    cluster.run_until_stable()
    return cluster, js


def test_failure_rules_order_fail_jobset_first_wins():
    """Rules-order test 1 (jobset_controller_test.go:690): FailJobSet
    listed before RestartJobSet with identical matchers fails the jobset
    immediately — restarts stays 0."""
    from jobset_tpu.api import FailurePolicyRule

    cluster, js = _rules_jobset("order1", [
        FailurePolicyRule(
            name="fail_first", action=keys.FAIL_JOBSET,
            on_job_failure_reasons=[keys.JOB_REASON_BACKOFF_LIMIT_EXCEEDED],
            target_replicated_jobs=["a"],
        ),
        FailurePolicyRule(
            name="restart_second", action=keys.RESTART_JOBSET,
            on_job_failure_reasons=[keys.JOB_REASON_BACKOFF_LIMIT_EXCEEDED],
            target_replicated_jobs=["a"],
        ),
    ])
    cluster.fail_job("default", "order1-a-0",
                     reason=keys.JOB_REASON_BACKOFF_LIMIT_EXCEEDED)
    cluster.run_until_stable()
    assert js.status.terminal_state == keys.JOBSET_FAILED
    assert js.status.restarts == 0
    assert js.status.restarts_count_towards_max == 0


def test_failure_rules_ignore_action_then_catchall_fail():
    """Rules-order test 3 (jobset_controller_test.go:765): an
    IgnoreMaxRestarts rule for rjob a plus a catch-all FailJobSet rule
    (EMPTY matcher lists match everything): repeated a-failures restart
    past max_restarts without counting, then one b-failure hits the
    catch-all and fails the jobset."""
    from jobset_tpu.api import FailurePolicyRule

    cluster, js = _rules_jobset("order3", [
        FailurePolicyRule(
            name="ignore_a", action=keys.RESTART_JOBSET_AND_IGNORE_MAX_RESTARTS,
            on_job_failure_reasons=[keys.JOB_REASON_BACKOFF_LIMIT_EXCEEDED],
            target_replicated_jobs=["a"],
        ),
        FailurePolicyRule(
            name="catch_all", action=keys.FAIL_JOBSET,
            on_job_failure_reasons=[], target_replicated_jobs=[],
        ),
    ], max_restarts=1)
    for expect_restarts in (1, 2, 3):  # well past max_restarts=1
        cluster.fail_job("default", "order3-a-0",
                         reason=keys.JOB_REASON_BACKOFF_LIMIT_EXCEEDED)
        cluster.run_until_stable()
        assert js.status.terminal_state == ""
        assert js.status.restarts == expect_restarts
        assert js.status.restarts_count_towards_max == 0
    cluster.fail_job("default", "order3-b-0")
    cluster.run_until_stable()
    assert js.status.terminal_state == keys.JOBSET_FAILED
    assert js.status.restarts == 3


def test_replicated_job_statuses_after_all_succeed():
    """replicatedJobsStatuses reflect completion (jobset_controller_test.go
    :1019): after every job succeeds, each rjob status shows
    succeeded == replicas and zero active/ready."""
    cluster, js = _two_rjob_cluster("statuses")
    cluster.complete_all_jobs(js)
    cluster.run_until_stable()
    by_name = {s.name: s for s in js.status.replicated_jobs_status}
    assert by_name["a"].succeeded == 2 and by_name["b"].succeeded == 3
    for s in by_name.values():
        assert s.active == 0 and s.ready == 0 and s.failed == 0


def test_managed_by_suspend_resume_and_status_preserved():
    """The managedBy contract (jobset_controller_test.go:1596-1663): the
    built-in controller creates nothing for an externally-managed JobSet —
    suspended OR resumed — and status written through the status
    subresource by the external controller is preserved verbatim."""
    from jobset_tpu.api.types import ReplicatedJobStatus

    cluster = make_cluster()
    cluster.add_topology("rack", num_domains=4, nodes_per_domain=2, capacity=8)
    js = _jobset("mb")
    js.spec.managed_by = "kueue.x-k8s.io/multikueue"
    js.spec.suspend = True
    cluster.create_jobset(js)
    cluster.run_until_stable()
    assert cluster.jobs == {} and cluster.services == {}

    live = cluster.get_jobset("default", "mb")
    live.spec.suspend = False  # unsuspend: STILL externally managed
    cluster.enqueue_reconcile("default", "mb")
    cluster.run_until_stable()
    assert cluster.jobs == {} and cluster.services == {}

    # External controller writes status through the subresource; the
    # built-in controller must not clobber it.
    want = live.status.__class__(
        restarts=1,
        replicated_jobs_status=[
            ReplicatedJobStatus(name="workers", ready=2, succeeded=3,
                                failed=4, active=5, suspended=6),
        ],
    )
    cluster.update_jobset_status("default", "mb", want)
    cluster.run_until_stable()
    got = cluster.get_jobset("default", "mb").status
    assert got.restarts == 1
    s = got.replicated_jobs_status[0]
    assert (s.ready, s.succeeded, s.failed, s.active, s.suspended) == \
        (2, 3, 4, 5, 6)
