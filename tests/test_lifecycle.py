"""Core lifecycle integration tests.

Mirrors the reference envtest scenarios
(test/integration/controller/jobset_controller_test.go): job materialization,
DNS service, status math, success policies, restart semantics, managedBy skip.
The cluster simulator plays the role envtest + jobUpdateFn play there.
"""

import pytest

from jobset_tpu.api import Coordinator, Network, SuccessPolicy, keys
from jobset_tpu.core import make_cluster
from jobset_tpu.core import metrics
from jobset_tpu.testing import make_jobset, make_replicated_job


@pytest.fixture(autouse=True)
def _reset_metrics():
    metrics.reset()
    yield


def two_rjob_jobset(name="js"):
    return (
        make_jobset(name)
        .replicated_job(
            make_replicated_job("leader").replicas(1).parallelism(1).completions(1).obj()
        )
        .replicated_job(
            make_replicated_job("workers").replicas(3).parallelism(2).completions(2).obj()
        )
        .obj()
    )


def default_cluster():
    cluster = make_cluster()
    cluster.add_topology("rack", num_domains=8, nodes_per_domain=4, capacity=16)
    return cluster


def test_jobs_created_with_identity_labels():
    cluster = default_cluster()
    js = cluster.create_jobset(two_rjob_jobset())
    cluster.run_until_stable()

    names = sorted(j.metadata.name for j in cluster.jobs.values())
    assert names == ["js-leader-0", "js-workers-0", "js-workers-1", "js-workers-2"]

    job = cluster.get_job("default", "js-workers-1")
    assert job.labels[keys.JOBSET_NAME_KEY] == "js"
    assert job.labels[keys.REPLICATED_JOB_NAME_KEY] == "workers"
    assert job.labels[keys.JOB_INDEX_KEY] == "1"
    assert job.labels[keys.RESTARTS_KEY] == "0"
    assert job.labels[keys.REPLICATED_JOB_REPLICAS_KEY] == "3"
    assert job.labels[keys.JOB_GLOBAL_INDEX_KEY] == "2"  # 1 leader + index 1
    assert len(job.labels[keys.JOB_KEY]) == 64
    # Pod template carries the same identity.
    assert job.spec.template.labels[keys.JOB_INDEX_KEY] == "1"
    # DNS default: subdomain set to jobset name.
    assert job.spec.template.spec.subdomain == "js"


def test_headless_service_created_with_defaults():
    cluster = default_cluster()
    cluster.create_jobset(two_rjob_jobset())
    cluster.run_until_stable()
    svc = cluster.get_service("default", "js")
    assert svc is not None
    assert svc.cluster_ip == "None"
    assert svc.selector == {keys.JOBSET_NAME_KEY: "js"}
    assert svc.publish_not_ready_addresses is True


def test_custom_subdomain_service():
    cluster = default_cluster()
    js = two_rjob_jobset()
    js.spec.network = Network(subdomain="net")
    cluster.create_jobset(js)
    cluster.run_until_stable()
    assert cluster.get_service("default", "net") is not None
    job = cluster.get_job("default", "js-leader-0")
    assert job.spec.template.spec.subdomain == "net"


def test_no_service_when_dns_disabled():
    cluster = default_cluster()
    js = two_rjob_jobset()
    js.spec.network = Network(enable_dns_hostnames=False)
    cluster.create_jobset(js)
    cluster.run_until_stable()
    assert cluster.services == {}


def test_pod_hostnames_resolve_via_service():
    """DNS contract: <js>-<rjob>-<jobIdx>-<podIdx>.<subdomain> reaches the pod
    (e2e ping analog, test/e2e/e2e_test.go:64-110)."""
    cluster = default_cluster()
    cluster.create_jobset(two_rjob_jobset())
    cluster.run_until_stable()
    pod = cluster.resolve_hostname("default", "js-workers-2-1.js")
    assert pod is not None
    assert pod.metadata.labels[keys.JOB_INDEX_KEY] == "2"
    assert pod.annotations[keys.POD_COMPLETION_INDEX_KEY] == "1"
    assert cluster.resolve_hostname("default", "js-workers-9-0.js") is None


def test_replicated_job_statuses_ready_math():
    cluster = default_cluster()
    js = cluster.create_jobset(two_rjob_jobset())
    cluster.run_until_stable()
    statuses = {s.name: s for s in js.status.replicated_jobs_status}
    assert statuses["leader"].ready == 1
    assert statuses["workers"].ready == 3
    assert statuses["workers"].active == 3


def test_success_policy_all_requires_every_job():
    cluster = default_cluster()
    js = cluster.create_jobset(two_rjob_jobset())
    cluster.run_until_stable()
    cluster.complete_job("default", "js-leader-0")
    cluster.run_until_stable()
    assert js.status.terminal_state == ""
    cluster.complete_all_jobs(js)
    cluster.run_until_stable()
    assert js.status.terminal_state == keys.JOBSET_COMPLETED
    assert cluster.jobset_has_condition(js, keys.JOBSET_COMPLETED)
    assert metrics.jobset_completed_total.value("default/js") == 1


def test_success_policy_any_targeted():
    cluster = default_cluster()
    js = two_rjob_jobset()
    js.spec.success_policy = SuccessPolicy(
        operator=keys.OPERATOR_ANY, target_replicated_jobs=["leader"]
    )
    cluster.create_jobset(js)
    cluster.run_until_stable()
    # a workers job completing does not match the policy
    cluster.complete_job("default", "js-workers-0")
    cluster.run_until_stable()
    assert js.status.terminal_state == ""
    cluster.complete_job("default", "js-leader-0")
    cluster.run_until_stable()
    assert js.status.terminal_state == keys.JOBSET_COMPLETED


def test_completed_jobset_deletes_active_jobs():
    cluster = default_cluster()
    js = two_rjob_jobset()
    js.spec.success_policy = SuccessPolicy(operator=keys.OPERATOR_ANY)
    cluster.create_jobset(js)
    cluster.run_until_stable()
    cluster.complete_job("default", "js-leader-0")
    cluster.run_until_stable()
    assert js.status.terminal_state == keys.JOBSET_COMPLETED
    # remaining active jobs were cleaned up
    assert all(j.finished()[0] for j in cluster.jobs.values())


def test_failure_without_policy_fails_jobset():
    cluster = default_cluster()
    js = cluster.create_jobset(two_rjob_jobset())
    cluster.run_until_stable()
    cluster.fail_job("default", "js-workers-1")
    cluster.run_until_stable()
    assert js.status.terminal_state == keys.JOBSET_FAILED
    cond = cluster.jobset_condition(js, keys.JOBSET_FAILED)
    assert cond.reason == keys.FAILED_JOBS_REASON
    assert "js-workers-1" in cond.message
    assert metrics.jobset_failed_total.value("default/js") == 1


def test_managed_by_external_controller_skipped():
    cluster = default_cluster()
    js = two_rjob_jobset()
    js.spec.managed_by = "kueue.x-k8s.io/multikueue"
    cluster.create_jobset(js)
    cluster.run_until_stable()
    assert cluster.jobs == {}
    assert cluster.services == {}


def test_events_emitted_after_status_updates():
    cluster = default_cluster()
    js = cluster.create_jobset(two_rjob_jobset())
    cluster.run_until_stable()
    cluster.complete_all_jobs(js)
    cluster.run_until_stable()
    reasons = [e.reason for e in cluster.events]
    assert keys.ALL_JOBS_COMPLETED_REASON in reasons


def test_coordinator_stamped_on_jobs_and_pods():
    cluster = default_cluster()
    js = two_rjob_jobset()
    js.spec.coordinator = Coordinator(replicated_job="leader", job_index=0, pod_index=0)
    cluster.create_jobset(js)
    cluster.run_until_stable()
    job = cluster.get_job("default", "js-workers-2")
    assert job.labels[keys.COORDINATOR_KEY] == "js-leader-0-0.js"
    pod = cluster.resolve_hostname("default", "js-workers-0-0.js")
    assert pod.annotations[keys.COORDINATOR_KEY] == "js-leader-0-0.js"


def test_domain_ownership_released_when_jobset_completes():
    """Regression (review): finished exclusive JobSets must free their
    topology domains for subsequent JobSets."""
    cluster = make_cluster()
    cluster.add_topology("rack", num_domains=2, nodes_per_domain=2, capacity=8)
    js_a = (
        make_jobset("a")
        .exclusive_placement("rack")
        .replicated_job(
            make_replicated_job("w").replicas(2).parallelism(2).completions(2).obj()
        )
        .obj()
    )
    cluster.create_jobset(js_a)
    cluster.run_until_stable()
    cluster.complete_all_jobs(js_a)
    cluster.run_until_stable()
    assert js_a.status.terminal_state == keys.JOBSET_COMPLETED
    occupied = {
        d for d, owners in cluster.domain_job_keys.get("rack", {}).items() if owners
    }
    assert occupied == set()

    js_b = (
        make_jobset("b")
        .exclusive_placement("rack")
        .replicated_job(
            make_replicated_job("w").replicas(2).parallelism(2).completions(2).obj()
        )
        .obj()
    )
    cluster.create_jobset(js_b)
    cluster.run_until_stable()
    assert all(p.spec.node_name for p in cluster.pods.values() if p.status.phase == "Running")
    assert sum(1 for p in cluster.pods.values() if p.spec.node_name) == 4


def test_update_jobset_preserves_status_and_creation_time():
    """Regression (review): spec updates must not wipe server-owned fields."""
    from jobset_tpu.api import FailurePolicy

    cluster = make_cluster()
    cluster.add_topology("rack", num_domains=4, nodes_per_domain=2, capacity=8)
    js = (
        make_jobset("js")
        .failure_policy(FailurePolicy(max_restarts=5))
        .replicated_job(
            make_replicated_job("w").replicas(2).parallelism(1).completions(1).obj()
        )
        .obj()
    )
    cluster.clock.advance(100)
    cluster.create_jobset(js)
    cluster.run_until_stable()
    cluster.fail_job("default", "js-w-0")
    cluster.run_until_stable()
    assert js.status.restarts == 1

    updated = js.clone()
    updated.spec.suspend = True
    cluster.update_jobset(updated)
    stored = cluster.get_jobset("default", "js")
    assert stored.status.restarts == 1
    assert stored.metadata.creation_time == 100.0


def test_churn_soak_leaves_no_index_residue():
    """Long-running-controller story: many JobSets through create ->
    complete -> TTL delete (with some gang restarts and failures mixed in)
    must leave every kernel index empty — a leak here grows controller
    memory forever at real-world churn rates."""
    from jobset_tpu.api import FailurePolicy

    cluster = make_cluster()
    cluster.add_topology("rack", num_domains=6, nodes_per_domain=4, capacity=16)

    for i in range(30):
        js = (
            make_jobset(f"churn-{i}")
            .failure_policy(FailurePolicy(max_restarts=2))
            .replicated_job(
                make_replicated_job("w")
                .replicas(2).parallelism(2).completions(2).obj()
            )
            .obj()
        )
        js.spec.ttl_seconds_after_finished = 5
        cluster.create_jobset(js)
        cluster.run_until_stable()
        if i % 3 == 1:  # a restart before completing
            cluster.fail_job("default", f"churn-{i}-w-0")
            cluster.run_until_stable()
        if i % 5 == 4:  # terminal failure path: fail until restarts exhaust
            while not cluster.jobset_has_condition(
                cluster.get_jobset("default", f"churn-{i}"), "Failed"
            ):
                cluster.fail_job("default", f"churn-{i}-w-0")
                cluster.run_until_stable()
        else:
            cluster.complete_all_jobs(cluster.get_jobset("default", f"churn-{i}"))
            cluster.run_until_stable()
        cluster.clock.advance(6)
        cluster.run_until_stable()
        assert cluster.get_jobset("default", f"churn-{i}") is None

    assert not cluster.jobsets
    assert not cluster.jobs
    assert not cluster.pods
    assert not cluster.pending_pod_keys
    assert not cluster.leader_pod_keys
    assert not cluster.dirty_job_uids
    assert not cluster.jobs_by_uid
    # Secondary indexes may keep empty buckets; they must hold no keys.
    assert not any(cluster.pods_by_job_key.values())
    assert not any(cluster.pods_by_base_name.values())
    assert not any(cluster.pods_by_job_uid.values())
    assert not any(cluster.jobs_by_owner.values())
    # Domain occupancy fully released.
    for domains in cluster.domain_job_keys.values():
        assert not any(domains.values()), domains
    # Node capacity fully returned.
    assert all(n.allocated == 0 for n in cluster.nodes.values())


def test_pod_failure_retried_within_backoff_limit():
    """A single pod failure frees its index for a retry (k8s Job
    semantics): the replacement pod binds and the JobSet still completes."""
    cluster = default_cluster()
    js = cluster.create_jobset(two_rjob_jobset("retry-js"))
    cluster.run_until_stable()
    victim = next(iter(cluster.pods.values()))
    cluster.fail_pod(victim.metadata.namespace, victim.metadata.name)
    cluster.run_until_stable()

    live = cluster.get_jobset("default", js.name)
    assert not live.status.terminal_state  # retried, not failed
    bound = sum(1 for p in cluster.pods.values()
                if p.spec.node_name and p.status.phase != "Failed")
    assert bound == sum(
        int(r.replicas) * r.template.spec.pods_expected()
        for r in live.spec.replicated_jobs
    )
    cluster.complete_all_jobs(live)
    cluster.run_until_stable()
    assert cluster.get_jobset("default", js.name).status.terminal_state == \
        keys.JOBSET_COMPLETED


def test_backoff_limit_exceeded_fails_job_organically():
    """Repeated pod failures past backoffLimit fail the job with
    BackoffLimitExceeded — organically driving the jobset failure path."""
    from jobset_tpu.testing import make_jobset, make_replicated_job

    cluster = default_cluster()
    rjob = make_replicated_job("w").replicas(1).parallelism(1).obj()
    rjob.template.spec.backoff_limit = 1
    js = make_jobset("bl").replicated_job(rjob).obj()
    cluster.create_jobset(js)
    cluster.run_until_stable()

    for _ in range(2):  # failures 1 and 2; limit is 1
        pod = next(p for p in cluster.pods.values()
                   if p.status.phase in ("Pending", "Running"))
        cluster.fail_pod(pod.metadata.namespace, pod.metadata.name)
        cluster.run_until_stable()

    live = cluster.get_jobset("default", "bl")
    assert live.status.terminal_state == keys.JOBSET_FAILED
    conds = [c for j in cluster.jobs_for_jobset(live)
             for c in j.status.conditions]
    assert any(c.reason == keys.JOB_REASON_BACKOFF_LIMIT_EXCEEDED
               for c in conds)


def test_pods_succeeding_complete_jobset_organically():
    """Succeeding every pod through succeed_pod (container exit-0 analog)
    completes each job at its completions count and the success policy
    marks the JobSet Completed — no complete_job drive involved."""
    cluster = default_cluster()
    js = cluster.create_jobset(two_rjob_jobset("organic-js"))
    cluster.run_until_stable()

    for pod in list(cluster.pods.values()):
        cluster.succeed_pod(pod.metadata.namespace, pod.metadata.name)
    cluster.run_until_stable()

    live = cluster.get_jobset("default", "organic-js")
    assert live.status.terminal_state == keys.JOBSET_COMPLETED
    for job in cluster.jobs_for_jobset(live):
        finished, kind = job.finished()
        assert finished and kind == "Complete"


def test_succeeded_index_survives_pod_record_deletion():
    """Completion credit is index-based and survives the Succeeded pod's
    record being deleted (drift enforcement deletes follower pods in any
    phase): the index is neither recreated nor its credit lost, and the
    job still completes once the remaining indexes succeed."""
    cluster = default_cluster()
    rjob = (
        make_replicated_job("w").replicas(1).parallelism(2).completions(2).obj()
    )
    js = make_jobset("keep-credit").replicated_job(rjob).obj()
    cluster.create_jobset(js)
    cluster.run_until_stable()

    pods = [p for p in cluster.pods.values()
            if p.status.phase in ("Pending", "Running")]
    assert len(pods) == 2
    first = min(pods, key=lambda p: p.completion_index())
    idx = first.completion_index()
    cluster.succeed_pod(first.metadata.namespace, first.metadata.name)
    cluster.run_until_stable()

    # Delete the Succeeded pod's record outright (what drift enforcement
    # may do) — the monotonic index set must retain the credit.
    cluster.delete_pod(first.metadata.namespace, first.metadata.name)
    cluster.run_until_stable()

    job = cluster.get_job("default", "keep-credit-w-0")
    assert idx in job.status.succeeded_indexes
    # The succeeded index was NOT recreated as a fresh pod.
    live_indexes = {p.completion_index() for p in cluster.pods.values()
                    if p.status.phase in ("Pending", "Running")}
    assert idx not in live_indexes
    assert job.status.succeeded == 1

    for pod in [p for p in cluster.pods.values()
                if p.status.phase in ("Pending", "Running")]:
        cluster.succeed_pod(pod.metadata.namespace, pod.metadata.name)
    cluster.run_until_stable()

    finished, kind = cluster.get_job("default", "keep-credit-w-0").finished()
    assert finished and kind == "Complete"
    live = cluster.get_jobset("default", "keep-credit")
    assert live.status.terminal_state == keys.JOBSET_COMPLETED
