"""KV-cache greedy decoding vs full-forward re-computation."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from jobset_tpu.models import TransformerConfig, init_params
from jobset_tpu.models.decode import build_generate
from jobset_tpu.models.transformer import build_forward
from jobset_tpu.parallel import MeshConfig, build_mesh


def _cfg():
    return TransformerConfig(
        vocab_size=64, d_model=32, n_heads=4, d_ff=64, n_layers=2,
        max_seq_len=64, dtype=jnp.float32, remat=False,
    )


@pytest.mark.parametrize("mesh_cfg", [MeshConfig(), MeshConfig(dp=2, tp=2)])
def test_greedy_decode_matches_full_forward(mesh_cfg):
    cfg = _cfg()
    mesh = build_mesh(mesh_cfg, jax.devices()[: mesh_cfg.num_devices])
    params = init_params(jax.random.key(0), cfg, mesh)
    max_new = 4

    prompt = jnp.asarray(
        np.random.default_rng(0).integers(0, cfg.vocab_size, (2, 5)), jnp.int32
    )
    generate = build_generate(cfg, mesh, max_new)
    got = np.asarray(generate(params, prompt))
    assert got.shape == (2, 5 + max_new)
    np.testing.assert_array_equal(got[:, :5], np.asarray(prompt))

    # Reference: re-run the full training forward on the growing sequence.
    forward = build_forward(cfg, mesh)
    seq = prompt
    for _ in range(max_new):
        logits = forward(params, seq)
        nxt = jnp.argmax(logits[:, -1].astype(jnp.float32), axis=-1)
        seq = jnp.concatenate([seq, nxt[:, None].astype(seq.dtype)], axis=1)
    np.testing.assert_array_equal(got, np.asarray(seq))


def test_generate_rejects_training_mesh_axes():
    cfg = _cfg()
    mesh = build_mesh(MeshConfig(sp=2), jax.devices()[:2])
    with pytest.raises(ValueError, match="sp=1"):
        build_generate(cfg, mesh, 2)


# ---------------------------------------------------------------------------
# MoE decode (VERDICT r1 weak #5): soft dispatch + top-k routed, vs the
# training forward re-computation, single- and multi-device.
# ---------------------------------------------------------------------------


def _moe_cfg(top_k: int):
    return TransformerConfig(
        vocab_size=64, d_model=32, n_heads=4, d_ff=64, n_layers=2,
        n_experts=4, d_ff_expert=32, moe_top_k=top_k,
        # Capacity that admits every routing choice: decode's
        # dense-all-experts path is the no-drop limit of the routed
        # training path, so the differential only holds drop-free.
        moe_capacity_factor=8.0,
        max_seq_len=64, dtype=jnp.float32, remat=False,
    )


@pytest.mark.parametrize("mesh_cfg", [MeshConfig(), MeshConfig(dp=2, tp=2)])
@pytest.mark.parametrize("top_k", [0, 2])
def test_moe_greedy_decode_matches_full_forward(mesh_cfg, top_k):
    cfg = _moe_cfg(top_k)
    mesh = build_mesh(mesh_cfg, jax.devices()[: mesh_cfg.num_devices])
    params = init_params(jax.random.key(1), cfg, mesh)
    max_new = 4

    prompt = jnp.asarray(
        np.random.default_rng(1).integers(0, cfg.vocab_size, (2, 5)), jnp.int32
    )
    generate = build_generate(cfg, mesh, max_new)
    got = np.asarray(generate(params, prompt))
    assert got.shape == (2, 5 + max_new)
    np.testing.assert_array_equal(got[:, :5], np.asarray(prompt))

    forward = build_forward(cfg, mesh)
    seq = prompt
    for _ in range(max_new):
        logits = forward(params, seq)
        nxt = jnp.argmax(logits[:, -1].astype(jnp.float32), axis=-1)
        seq = jnp.concatenate([seq, nxt[:, None].astype(seq.dtype)], axis=1)
    np.testing.assert_array_equal(got, np.asarray(seq))


def test_sorted_ragged_prefill_matches_dense_formulation():
    """The sorted ragged top-k dispatch (prefill) and the dense-all-experts
    chain (decode step) are two formulations of the same per-token math —
    they must agree on identical inputs, including when tokens concentrate
    onto few experts (ragged group sizes far from uniform)."""
    from jobset_tpu.models.decode import (
        _moe_mlp_topk_decode,
        _moe_mlp_topk_sorted,
    )

    import dataclasses

    mesh = build_mesh(MeshConfig(), jax.devices()[:1])
    rng = np.random.default_rng(3)
    # bf16 is the real serving dtype — the tolerance covers the two
    # formulations' different (both f32-accumulated) contraction orders.
    for dtype, tol in ((jnp.float32, 2e-5), (jnp.bfloat16, 2e-2)):
        cfg = dataclasses.replace(_moe_cfg(2), dtype=dtype)
        params = init_params(jax.random.key(0), cfg, mesh)
        layer0 = jax.tree.map(lambda a: a[0][0], params["layers"])

        for case, x in {
            "spread": rng.standard_normal((2, 9, cfg.d_model)),
            # Near-identical tokens: the router sends everything to the
            # same k experts, making one ragged group hold every slot.
            "concentrated": np.broadcast_to(
                rng.standard_normal((1, 1, cfg.d_model)), (2, 9, cfg.d_model)
            ) + 1e-3 * rng.standard_normal((2, 9, cfg.d_model)),
        }.items():
            xn = jnp.asarray(x, jnp.float32)

            def run(fn, xn):
                return jax.jit(
                    jax.shard_map(
                        lambda v: fn(layer0, v, cfg),
                        mesh=mesh,
                        in_specs=P(),
                        out_specs=P(),
                        check_vma=False,
                    )
                )(xn)

            dense = run(_moe_mlp_topk_decode, xn)
            ragged = run(_moe_mlp_topk_sorted, xn)
            np.testing.assert_allclose(
                np.asarray(ragged, np.float32),
                np.asarray(dense, np.float32),
                rtol=tol, atol=tol,
                err_msg=f"{case}/{dtype.__name__}",
            )


def test_topk_equals_soft_dispatch_when_k_is_all_experts():
    """k = n_experts: renormalized top-k weights are exactly the softmax
    gates, so the routed decode must reproduce the soft-dispatch decode."""
    mesh = build_mesh(MeshConfig(), jax.devices()[:1])
    prompt = jnp.asarray(
        np.random.default_rng(2).integers(0, 64, (2, 5)), jnp.int32
    )
    outs = []
    for top_k in (0, 4):
        cfg = _moe_cfg(top_k)
        params = init_params(jax.random.key(2), cfg, mesh)
        generate = build_generate(cfg, mesh, 4)
        outs.append(np.asarray(generate(params, prompt)))
    np.testing.assert_array_equal(outs[0], outs[1])


def test_zero_new_tokens_returns_prompt_unchanged():
    """max_new_tokens=0 honors the [B, T_prompt + max_new_tokens] contract:
    prefill-only, prompt comes back as-is."""
    cfg = _cfg()
    mesh = build_mesh(MeshConfig(), jax.devices()[:1])
    params = init_params(jax.random.key(0), cfg, mesh)
    prompt = jnp.asarray(
        np.random.default_rng(1).integers(0, cfg.vocab_size, (2, 6)), jnp.int32
    )
    out = np.asarray(build_generate(cfg, mesh, max_new_tokens=0)(params, prompt))
    np.testing.assert_array_equal(out, np.asarray(prompt))


def test_topk1_sampling_equals_greedy_on_sharded_vocab():
    """top_k=1 masks everything but the global max, so any temperature must
    reproduce greedy exactly — including across tp vocab shards."""
    cfg = _cfg()
    mc = MeshConfig(tp=2)
    mesh = build_mesh(mc, jax.devices()[:2])
    params = init_params(jax.random.key(0), cfg, mesh)
    prompt = jnp.asarray(
        np.random.default_rng(2).integers(0, cfg.vocab_size, (2, 5)), jnp.int32
    )
    greedy = build_generate(cfg, mesh, 6)(params, prompt)
    sampled = build_generate(cfg, mesh, 6, temperature=1.7, top_k=1)(
        params, prompt, jax.random.key(7)
    )
    np.testing.assert_array_equal(np.asarray(sampled), np.asarray(greedy))


def test_topk_keeps_exactly_k_on_ties():
    """Tied logits straddling the k-th value must NOT widen the candidate
    set: exact-k semantics break ties by lowest vocab index, so with
    logits [9, 9, 9, 9, ...] (all equal) and top_k=2 only tokens 0 and 1
    are ever sampled, across shards and many draws."""
    from jobset_tpu.models.decode import _pick_token

    mc = MeshConfig(tp=2)
    mesh = build_mesh(mc, jax.devices()[:2])
    v_global = 16
    logits = jnp.full((3, v_global), 9.0, jnp.float32)  # every logit tied

    # key is a jitted ARGUMENT (not a closure constant) so the program
    # compiles once across the 40 draws.
    run = jax.jit(
        jax.shard_map(
            lambda lg, key: _pick_token(lg, key, 4, temperature=1.3, top_k=2),
            mesh=mesh,
            in_specs=(P(None, "tp"), P()),
            out_specs=P(None),
            # the psum'd argmax is tp-invariant but the checker can't
            # prove replication over the unused axes statically
            check_vma=False,
        )
    )

    seen = set()
    for seed in range(40):
        toks = np.asarray(run(logits, jax.random.key(seed)))
        seen.update(toks.ravel().tolist())
    assert seen <= {0, 1}, seen  # exact-k: only the two lowest indices
    assert seen == {0, 1}, seen  # and both genuinely reachable


def test_sampling_frequencies_track_softmax():
    """Gumbel-max sampling draws from softmax(logits/T): over many seeds the
    first sampled token's empirical distribution must correlate with the
    model's actual softmax at that position."""
    cfg = _cfg()
    mesh = build_mesh(MeshConfig(), jax.devices()[:1])
    params = init_params(jax.random.key(0), cfg, mesh)
    prompt = jnp.asarray(
        np.random.default_rng(3).integers(0, cfg.vocab_size, (1, 4)), jnp.int32
    )
    logits = np.asarray(build_forward(cfg, mesh)(params, prompt))[0, -1]
    probs = np.exp(logits - logits.max())
    probs /= probs.sum()

    gen = build_generate(cfg, mesh, 1, temperature=1.0)
    picks = [
        int(np.asarray(gen(params, prompt, jax.random.key(s)))[0, -1])
        for s in range(300)
    ]
    freq = np.bincount(picks, minlength=cfg.vocab_size) / len(picks)
    # Coarse agreement: the sampled mode is a high-probability token and
    # the correlation is strong (300 draws; not a tight GoF test).
    assert probs[np.argmax(freq)] > 0.5 * probs.max()
    assert np.corrcoef(freq, probs)[0, 1] > 0.7


def test_gqa_greedy_decode_matches_full_forward():
    """GQA decode (compact KV cache + broadcast-on-read) agrees with the
    full training forward's argmax continuation, on a tp-sharded mesh."""
    cfg = TransformerConfig(
        vocab_size=64, d_model=32, n_heads=4, n_kv_heads=2, d_ff=64,
        n_layers=2, max_seq_len=64, dtype=jnp.float32, remat=False,
    )
    mc = MeshConfig(tp=2)
    mesh = build_mesh(mc, jax.devices()[:2])
    cfg.validate(mc)
    params = init_params(jax.random.key(0), cfg, mesh)
    prompt = jnp.asarray(
        np.random.default_rng(4).integers(0, cfg.vocab_size, (2, 5)), jnp.int32
    )
    out = np.asarray(build_generate(cfg, mesh, 5)(params, prompt))

    fwd = build_forward(cfg, mesh)
    toks = np.asarray(prompt)
    for _ in range(5):
        logits = np.asarray(fwd(params, jnp.asarray(toks)))
        toks = np.concatenate([toks, logits[:, -1].argmax(-1)[:, None]], axis=1)
    np.testing.assert_array_equal(out, toks)


def test_tied_embeddings_decode_matches_full_forward():
    cfg = TransformerConfig(
        vocab_size=64, d_model=32, n_heads=4, d_ff=64, n_layers=2,
        max_seq_len=64, dtype=jnp.float32, remat=False, tie_embeddings=True,
    )
    mc = MeshConfig(tp=2)
    mesh = build_mesh(mc, jax.devices()[:2])
    params = init_params(jax.random.key(0), cfg, mesh)
    prompt = jnp.asarray(
        np.random.default_rng(6).integers(0, cfg.vocab_size, (2, 5)), jnp.int32
    )
    out = np.asarray(build_generate(cfg, mesh, 5)(params, prompt))
    fwd = build_forward(cfg, mesh)
    toks = np.asarray(prompt)
    for _ in range(5):
        logits = np.asarray(fwd(params, jnp.asarray(toks)))
        toks = np.concatenate([toks, logits[:, -1].argmax(-1)[:, None]], axis=1)
    np.testing.assert_array_equal(out, toks)


# ---------------------------------------------------------------------------
# Weight-only int8 serving quantization (models/quant.py)
# ---------------------------------------------------------------------------


def test_quantize_int8_error_bound():
    """Per-output-channel symmetric int8: reconstruction error per element
    is bounded by half a quantization step of its channel."""
    from jobset_tpu.models.quant import quantize_int8, weight_cast

    rng = np.random.default_rng(0)
    w = jnp.asarray(rng.standard_normal((3, 64, 32)) * 0.07, jnp.float32)
    qt = quantize_int8(w)
    assert qt.q.dtype == jnp.int8 and qt.q.shape == w.shape
    assert qt.scale.shape == (3, 1, 32)
    back = weight_cast(qt, jnp.float32)
    step = np.asarray(qt.scale)
    err = np.abs(np.asarray(back) - np.asarray(w))
    assert (err <= step / 2 + 1e-7).all(), float(err.max())


def test_quantized_forward_logits_close_to_full_precision():
    """End-to-end logits with int8 weights stay within int8 resolution of
    the full-precision forward (same bf16 compute path both sides)."""
    from jobset_tpu.models.quant import quantize_params_for_serving

    cfg = _cfg()
    mesh = build_mesh(MeshConfig(), jax.devices()[:1])
    params = init_params(jax.random.key(0), cfg, mesh)
    params_q = quantize_params_for_serving(params)

    tokens = jnp.asarray(
        np.random.default_rng(1).integers(0, cfg.vocab_size, (2, 8)), jnp.int32
    )
    forward = build_forward(cfg, mesh)
    fp = np.asarray(forward(params, tokens)).astype(np.float32)
    q = np.asarray(forward(params_q, tokens)).astype(np.float32)
    # int8 weight noise is ~0.4% per matmul; a few layers compound to a
    # small fraction of the logits' dynamic range.
    scale = np.abs(fp).max()
    assert np.abs(q - fp).max() <= 0.05 * scale, (
        float(np.abs(q - fp).max()), float(scale)
    )


def test_quantized_decode_runs_sharded_and_tracks_full_precision():
    """build_generate(quantized=True) on a dp x tp serving mesh: memory
    halves (int8 weights), outputs are valid ids, and greedy picks match
    the full-precision decode wherever the fp logit margin exceeds the
    quantization noise (ties may legitimately flip)."""
    from jobset_tpu.models.quant import (
        QuantizedTensor,
        quantize_params_for_serving,
    )

    cfg = _cfg()
    mc = MeshConfig(dp=1, tp=2)
    mesh = build_mesh(mc, jax.devices()[: mc.num_devices])
    params = init_params(jax.random.key(0), cfg, mesh)
    params_q = quantize_params_for_serving(params)

    def nbytes(tree):
        return sum(
            leaf.nbytes for leaf in jax.tree.leaves(tree)
        )

    assert nbytes(params_q) < 0.6 * nbytes(params)
    assert any(
        isinstance(leaf, QuantizedTensor)
        for leaf in jax.tree.leaves(
            params_q, is_leaf=lambda x: isinstance(x, QuantizedTensor)
        )
    )

    prompt = jnp.asarray(
        np.random.default_rng(2).integers(0, cfg.vocab_size, (2, 5)), jnp.int32
    )
    max_new = 6
    fp_gen = build_generate(cfg, mesh, max_new)
    q_gen = build_generate(cfg, mesh, max_new, quantized=True)
    fp_out = np.asarray(fp_gen(params, prompt))
    q_out = np.asarray(q_gen(params_q, prompt))
    assert q_out.shape == fp_out.shape
    assert ((q_out >= 0) & (q_out < cfg.vocab_size)).all()
    np.testing.assert_array_equal(q_out[:, :5], np.asarray(prompt))
    agree = (q_out == fp_out).mean()
    assert agree >= 0.5, f"quantized decode diverged everywhere ({agree=})"


def test_quantized_kv_cache_decode_tracks_full_precision():
    """build_generate(quantized_kv=True): the int8 per-vector KV cache
    (the dominant long-context memory term) tracks the full-precision
    decode on a dp x tp serving mesh."""
    cfg = _cfg()
    mc = MeshConfig(dp=1, tp=2)
    mesh = build_mesh(mc, jax.devices()[: mc.num_devices])
    params = init_params(jax.random.key(0), cfg, mesh)

    prompt = jnp.asarray(
        np.random.default_rng(3).integers(0, cfg.vocab_size, (2, 5)), jnp.int32
    )
    max_new = 6
    fp_out = np.asarray(build_generate(cfg, mesh, max_new)(params, prompt))
    kv8_out = np.asarray(
        build_generate(cfg, mesh, max_new, quantized_kv=True)(params, prompt)
    )
    assert kv8_out.shape == fp_out.shape
    assert ((kv8_out >= 0) & (kv8_out < cfg.vocab_size)).all()
    np.testing.assert_array_equal(kv8_out[:, :5], np.asarray(prompt))
    agree = (kv8_out == fp_out).mean()
    assert agree >= 0.5, f"kv8 decode diverged everywhere ({agree=})"

    # Cache memory: int8 q + one f32 scale per vector ~ halves bf16 cache
    # bytes at the flagship head_dim.
    from jobset_tpu.models.decode import init_kv_cache

    fp_cache = init_kv_cache(cfg, mesh, 2, 16)
    q_cache = init_kv_cache(cfg, mesh, 2, 16, quantized_kv=True)
    nbytes = lambda t: sum(l.nbytes for l in jax.tree.leaves(t))  # noqa: E731
    assert nbytes(q_cache) < 0.75 * nbytes(fp_cache)


def test_quantized_weights_and_kv_cache_compose():
    """Weights int8 + cache int8 together (the full quantized serving
    stack) still produce valid decodes on the sharded mesh."""
    from jobset_tpu.models.quant import quantize_params_for_serving

    cfg = _cfg()
    mc = MeshConfig(dp=1, tp=2)
    mesh = build_mesh(mc, jax.devices()[: mc.num_devices])
    params = quantize_params_for_serving(
        init_params(jax.random.key(0), cfg, mesh)
    )
    prompt = jnp.asarray(
        np.random.default_rng(4).integers(0, cfg.vocab_size, (2, 4)), jnp.int32
    )
    out = np.asarray(
        build_generate(cfg, mesh, 5, quantized=True, quantized_kv=True)(
            params, prompt
        )
    )
    assert out.shape == (2, 9)
    assert ((out >= 0) & (out < cfg.vocab_size)).all()
    np.testing.assert_array_equal(out[:, :4], np.asarray(prompt))


def test_quantized_kv_cache_with_gqa_tracks_full_precision():
    """int8 KV cache + GQA (n_kv_heads < n_heads): the compact quantized
    cache is dequantized then broadcast per query-head group — per-kv-head
    scales must survive tp sharding and repeat_kv ordering."""
    cfg = TransformerConfig(
        vocab_size=128, d_model=64, n_heads=4, n_kv_heads=2, d_ff=128,
        n_layers=2, max_seq_len=24,
    )
    mc = MeshConfig(dp=1, tp=2)
    mesh = build_mesh(mc, jax.devices()[: mc.num_devices])
    params = init_params(jax.random.key(0), cfg, mesh)
    prompt = jnp.asarray(
        np.random.default_rng(5).integers(0, cfg.vocab_size, (2, 5)), jnp.int32
    )
    fp = np.asarray(build_generate(cfg, mesh, 6)(params, prompt))
    kv8 = np.asarray(
        build_generate(cfg, mesh, 6, quantized_kv=True)(params, prompt)
    )
    assert kv8.shape == fp.shape
    agree = (kv8 == fp).mean()
    assert agree >= 0.5, f"GQA kv8 decode diverged everywhere ({agree=})"


def test_quantized_kv_decode_logits_error_bounded():
    """Pin the compounded int8+bf16 rounding on the quantized-KV serving
    path (round-3 advisor): `_cache_read` dequantizes int8 KV straight to
    the bf16 compute dtype, so each int8*scale product is rounded to 8
    mantissa bits before the attention matmul. A teacher-forced
    per-step LOGITS comparison (same params, same token stream, plain
    bf16 cache vs int8 cache) bounds the accumulated error — tighter
    evidence than the end-to-end token-agreement test, which tolerates
    divergence after one near-tie pick."""
    import dataclasses

    from jobset_tpu.models.decode import (
        _prefill_logits,
        _token_logits,
        init_kv_cache,
    )

    cfg = dataclasses.replace(_cfg(), dtype=jnp.bfloat16)
    mesh = build_mesh(MeshConfig(), jax.devices()[:1])
    params = init_params(jax.random.key(0), cfg, mesh)
    rng = np.random.default_rng(9)
    batch, t_prompt, t_total = 2, 5, 12
    tokens = jnp.asarray(
        rng.integers(0, cfg.vocab_size, (batch, t_total)), jnp.int32
    )

    def teacher_forced_logits(quantized_kv):
        cache0 = init_kv_cache(
            cfg, mesh, batch, t_total, quantized_kv=quantized_kv
        )

        def local(params, tokens, cache):
            outs = []
            last, cache = _prefill_logits(
                params, tokens[:, :t_prompt], cache, cfg
            )
            outs.append(last)
            for pos in range(t_prompt, t_total):
                last, cache = _token_logits(
                    params, tokens[:, pos], cache, pos, cfg
                )
                outs.append(last)
            return jnp.stack(outs)

        return np.asarray(
            jax.jit(
                jax.shard_map(
                    local, mesh=mesh, in_specs=(P(), P(), P()),
                    out_specs=P(), check_vma=False,
                )
            )(params, tokens, cache0),
            np.float32,
        )

    fp = teacher_forced_logits(False)
    q8 = teacher_forced_logits(True)
    scale = np.abs(fp).max()
    rel = np.abs(fp - q8).max() / scale
    assert rel < 0.1, f"quantized-KV logit error {rel=:.4f} vs scale {scale:.3f}"
    # Greedy picks must agree at almost every teacher-forced step.
    agree = (fp.argmax(-1) == q8.argmax(-1)).mean()
    assert agree >= 0.85, f"teacher-forced argmax agreement {agree=}"
