#!/usr/bin/env python
"""External-controller example: the SDK + informer pattern.

The analog of the reference's `examples/client-go/main.go` (a Go program
that creates a JobSet through the generated clientset) — extended to show
the watch/informer machinery an external controller (e.g. a queueing
system like Kueue/MultiKueue) builds on: create a JobSet through the typed
client, react to its lifecycle through a `JobSetInformer` without polling,
and clean up when it completes.

Run it self-contained (it boots an in-process controller server — the
simulated cluster has no kubelet, so the script also plays the role of
"something finishes the jobs" by driving their completion):

    python examples/external_controller.py
"""

from __future__ import annotations

import argparse
import sys
import threading

from jobset_tpu.client import JobSetClient, JobSetInformer
from jobset_tpu.testing import make_jobset, make_replicated_job


def build_jobset():
    return (
        make_jobset("external-demo")
        .replicated_job(
            make_replicated_job("workers")
            .replicas(2)
            .parallelism(2)
            .completions(2)
            .obj()
        )
        .obj()
    )


def main() -> int:
    argparse.ArgumentParser(description=__doc__).parse_args()

    from jobset_tpu.server import ControllerServer

    server = ControllerServer("127.0.0.1:0", tick_interval=0.05).start()
    print(f"booted in-process controller at {server.address}")

    client = JobSetClient(server.address)
    completed = threading.Event()
    deleted = threading.Event()

    # The informer fires handlers from its watch thread — an external
    # controller would enqueue reconcile work here instead of printing.
    def on_update(old, new):
        conds = {
            c["type"]: c["status"]
            for c in new.get("status", {}).get("conditions", [])
        }
        print(f"observed update: restarts="
              f"{new.get('status', {}).get('restarts', 0)} conditions={conds}")
        if conds.get("Completed") == "True":
            completed.set()

    def on_delete(js):
        print(f"observed delete: {js['metadata']['name']}")
        deleted.set()

    informer = JobSetInformer(
        client,
        on_add=lambda js: print(f"observed add: {js['metadata']['name']}"),
        on_update=on_update,
        on_delete=on_delete,
        poll_timeout=1.0,
    ).start()

    js = build_jobset()
    created = client.create(js)
    print(f"created {created.metadata.name} (uid {created.metadata.uid})")

    # The in-process simulator has no kubelet, so drive the child jobs to
    # completion the way the integration suite does: under the server lock
    # (the background pump thread reconciles every tick), then refresh the
    # watch journal so the informer sees the status transition.
    import time

    deadline = time.monotonic() + 10
    while not server.cluster.jobs and time.monotonic() < deadline:
        time.sleep(0.1)
    with server.lock:
        js_live = server.cluster.get_jobset("default", "external-demo")
        server.cluster.complete_all_jobs(js_live)
        server.cluster.run_until_stable()
        server._refresh_watch_locked()

    if not completed.wait(timeout=30):
        print("JobSet did not complete in time", file=sys.stderr)
        return 1
    print("JobSet completed — deleting")
    client.delete("external-demo")
    if not deleted.wait(timeout=30):
        print("delete event not observed in time", file=sys.stderr)
        return 1

    informer.stop()
    server.stop()
    print("done")
    return 0


if __name__ == "__main__":
    sys.exit(main())
