#!/usr/bin/env python
"""External-controller example: the SDK + informer pattern.

The analog of the reference's `examples/client-go/main.go` (a Go program
that creates a JobSet through the generated clientset) — extended to show
the watch/informer machinery an external controller (e.g. a queueing
system like Kueue/MultiKueue) builds on: create a JobSet through the typed
client, react to its lifecycle through a `JobSetInformer`, and observe the
CHILD jobs/pods through `JobInformer`/`PodInformer` (the client-go
generated-informer analog) — fully event-driven, no polling anywhere.

Run it self-contained (it boots an in-process controller server — the
simulated cluster has no kubelet, so the script also plays the role of
"something finishes the jobs" by driving their completion):

    python examples/external_controller.py
"""

from __future__ import annotations

import argparse
import sys
import threading

from jobset_tpu.client import (
    EventInformer,
    JobInformer,
    JobSetClient,
    JobSetInformer,
    PodInformer,
    ServiceInformer,
)
from jobset_tpu.testing import make_jobset, make_replicated_job


def build_jobset():
    return (
        make_jobset("external-demo")
        .replicated_job(
            make_replicated_job("workers")
            .replicas(2)
            .parallelism(2)
            .completions(2)
            .obj()
        )
        .obj()
    )


def main() -> int:
    argparse.ArgumentParser(description=__doc__).parse_args()

    from jobset_tpu.server import ControllerServer

    server = ControllerServer("127.0.0.1:0", tick_interval=0.05).start()
    print(f"booted in-process controller at {server.address}")

    client = JobSetClient(server.address)
    completed = threading.Event()
    deleted = threading.Event()

    # The informer fires handlers from its watch thread — an external
    # controller would enqueue reconcile work here instead of printing.
    def on_update(old, new):
        conds = {
            c["type"]: c["status"]
            for c in new.get("status", {}).get("conditions", [])
        }
        print(f"observed update: restarts="
              f"{new.get('status', {}).get('restarts', 0)} conditions={conds}")
        if conds.get("Completed") == "True":
            completed.set()

    def on_delete(js):
        print(f"observed delete: {js['metadata']['name']}")
        deleted.set()

    informer = JobSetInformer(
        client,
        on_add=lambda js: print(f"observed add: {js['metadata']['name']}"),
        on_update=on_update,
        on_delete=on_delete,
        poll_timeout=1.0,
    ).start()

    # Child watches: an external controller reacts to job/pod state through
    # events, never by polling GETs.
    children_ready = threading.Event()
    child_jobs: set[str] = set()

    def on_child_job(job):
        child_jobs.add(job["metadata"]["name"])
        print(f"observed child job: {job['metadata']['name']}")
        if len(child_jobs) >= 2:  # both replicas materialized
            children_ready.set()

    job_informer = JobInformer(
        client, on_add=on_child_job, poll_timeout=1.0
    ).start()
    pod_informer = PodInformer(
        client,
        on_add=lambda p: print(f"observed child pod: {p['metadata']['name']}"),
        poll_timeout=1.0,
    ).start()

    # Services and cluster events complete the watchable surface (client-go
    # generates informers for every type): the reconciler's headless
    # DNS-rendezvous service arrives as a watch event, and the lifecycle
    # event stream replaces any GET /api/v1/events polling.
    svc_seen = threading.Event()
    service_informer = ServiceInformer(
        client,
        on_add=lambda s: (
            print(f"observed headless service: {s['metadata']['name']}"),
            svc_seen.set(),
        ),
        poll_timeout=1.0,
    ).start()
    event_informer = EventInformer(
        client,
        on_add=lambda e: print(
            f"observed cluster event: {e['reason']} ({e['type']})"
        ),
        poll_timeout=1.0,
    ).start()

    js = build_jobset()
    created = client.create(js)
    print(f"created {created.metadata.name} (uid {created.metadata.uid})")

    # Event-driven rendezvous with the children (the JobInformer fires as
    # the reconciler materializes them — no polling loop). The in-process
    # simulator has no kubelet, so once they exist this script drives their
    # completion under the server lock, then refreshes the watch journal so
    # the informers see the status transition.
    if not children_ready.wait(timeout=10):
        print("child jobs never observed", file=sys.stderr)
        return 1
    if not svc_seen.wait(timeout=10):
        print("headless service never observed", file=sys.stderr)
        return 1
    with server.lock:
        js_live = server.cluster.get_jobset("default", "external-demo")
        server.cluster.complete_all_jobs(js_live)
        server.cluster.run_until_stable()
        server._refresh_watch_locked()

    if not completed.wait(timeout=30):
        print("JobSet did not complete in time", file=sys.stderr)
        return 1
    print("JobSet completed — deleting")
    client.delete("external-demo")
    if not deleted.wait(timeout=30):
        print("delete event not observed in time", file=sys.stderr)
        return 1

    informer.stop()
    job_informer.stop()
    pod_informer.stop()
    service_informer.stop()
    event_informer.stop()
    server.stop()
    print("done")
    return 0


if __name__ == "__main__":
    sys.exit(main())
