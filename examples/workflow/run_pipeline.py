#!/usr/bin/env python
"""Workflow-step JobSet orchestration (examples/argo-workflow analog).

Drives `pipeline.yaml`: each step creates a JobSet through the typed
client and WATCHES (long-poll, no polling loop) until its
successCondition or failureCondition — expressions over the JobSet
status, the same contract Argo's resource template evaluates
(`successCondition: status.terminalState == Completed`) — holds. Steps
run strictly in order; a failed condition stops the pipeline.

Run it self-contained (boots an in-process controller; the simulated
cluster has no kubelet, so the script also plays "the workload
finishes" by completing each step's jobs):

    python examples/workflow/run_pipeline.py
"""

from __future__ import annotations

import argparse
import os
import sys
import threading

import yaml

from jobset_tpu.client import JobSetClient, JobSetInformer

# The condition language is the subset the reference example uses:
# `status.<field> == <value>` (k8s field-selector style).
def _condition_holds(manifest: dict, expr: str) -> bool:
    lhs, _, rhs = expr.partition("==")
    path, value = lhs.strip().split("."), rhs.strip()
    node = manifest
    for part in path:
        node = node.get(part, {}) if isinstance(node, dict) else {}
    return node == value


def run_step(client, server, step: dict, timeout: float = 30.0) -> bool:
    """Create the step's JobSet; watch until success/failure condition."""
    manifest = step["manifest"]
    name = manifest["metadata"]["name"]
    outcome: dict = {}
    decided = threading.Event()

    def check(js: dict) -> None:
        # Gate on THIS step's JobSet only: the informer also fires for
        # earlier steps' (still present, already Completed) JobSets.
        if js.get("metadata", {}).get("name") != name:
            return
        if _condition_holds(js, step["failureCondition"]):
            outcome["ok"] = False
            decided.set()
        elif _condition_holds(js, step["successCondition"]):
            outcome["ok"] = True
            decided.set()

    informer = JobSetInformer(
        client,
        on_add=check,
        on_update=lambda _old, new: check(new),
        poll_timeout=1.0,
    ).start()
    try:
        created = client.create(yaml.safe_dump(manifest))
        print(f"step {step['name']}: created JobSet {created.metadata.name}")

        # No kubelet in the simulator: complete the jobs so the JobSet
        # reaches its terminal state (a real deployment's workloads do
        # this by finishing).
        with server.lock:
            js = server.cluster.get_jobset("default", name)
            server.cluster.complete_all_jobs(js)
        server.pump()  # reconcile to terminal state + refresh the journal

        if not decided.wait(timeout):
            print(f"step {step['name']}: no condition held in time",
                  file=sys.stderr)
            return False
        print(f"step {step['name']}: "
              f"{'succeeded' if outcome['ok'] else 'FAILED'}")
        return outcome["ok"]
    finally:
        informer.stop()


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument(
        "pipeline", nargs="?",
        default=os.path.join(os.path.dirname(__file__), "pipeline.yaml"),
    )
    args = parser.parse_args()

    from jobset_tpu.server import ControllerServer

    with open(args.pipeline) as f:
        pipeline = yaml.safe_load(f)

    server = ControllerServer("127.0.0.1:0", tick_interval=0.05).start()
    client = JobSetClient(server.address)
    print(f"pipeline {pipeline['metadata']['name']}: "
          f"{len(pipeline['steps'])} steps at {server.address}")

    ok = True
    for step in pipeline["steps"]:
        if not run_step(client, server, step):
            ok = False
            break
    server.stop()
    print("pipeline", "completed" if ok else "failed")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
