#!/usr/bin/env python
"""Serving-path walkthrough: train briefly, then generate.

The reference has no inference surface (it orchestrates containers); this
demo shows the workload plane's serving half end-to-end on the simulated
backend: train the flagship transformer on a tiny repeating corpus, then
decode from it through `models.decode.build_generate` — batched prefill,
compact (GQA) KV cache, greedy decoding, and temperature/top-k sampling.

    python examples/serve_demo.py
"""

from __future__ import annotations

import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np
    import optax

    from jobset_tpu.models import TransformerConfig, init_params
    from jobset_tpu.models.decode import build_generate
    from jobset_tpu.models.transformer import build_train_step
    from jobset_tpu.parallel import MeshConfig, build_mesh
    from jobset_tpu.runtime.data import TokenDataset, write_token_file

    vocab = 16
    cfg = TransformerConfig(
        vocab_size=vocab, d_model=64, n_heads=4, n_kv_heads=2, d_ff=128,
        n_layers=2, max_seq_len=64, dtype=jnp.float32, remat=False,
    )

    # Train on a repeating 0..15 pattern — trivially learnable, so a few
    # dozen steps make the continuation predictable.
    with tempfile.TemporaryDirectory() as d:
        corpus = os.path.join(d, "corpus.bin")
        write_token_file(corpus, np.tile(np.arange(vocab), 400))
        mesh = build_mesh(MeshConfig(tp=2), jax.devices()[:2])
        cfg.validate(MeshConfig(tp=2))
        ds = TokenDataset(corpus, seq_len=32, batch_size=8, vocab_size=vocab)
        params = init_params(jax.random.key(0), cfg, mesh)
        opt = optax.adamw(3e-3)
        opt_state = opt.init(params)
        step = build_train_step(cfg, mesh, opt)
        for s in range(60):
            params, opt_state, loss = step(params, opt_state, ds.batch(s))
        print(f"trained 60 steps, final loss {float(loss):.3f}")

        prompt = jnp.asarray([[4, 5, 6, 7], [11, 12, 13, 14]], jnp.int32)

        greedy = build_generate(cfg, mesh, max_new_tokens=8)
        out = np.asarray(greedy(params, prompt))
        print("greedy:")
        for row in out:
            print("  ", " ".join(f"{t:2d}" for t in row))
        # The learned pattern continues each prompt modulo the vocab.
        expect0 = [(7 + i + 1) % vocab for i in range(8)]
        if list(out[0, 4:]) != expect0:
            print(f"unexpected continuation (wanted {expect0})", file=sys.stderr)
            return 1

        sampler = build_generate(
            cfg, mesh, max_new_tokens=8, temperature=0.9, top_k=4
        )
        print("sampled (temperature 0.9, top_k 4, three seeds):")
        for seed in range(3):
            out = np.asarray(sampler(params, prompt, jax.random.key(seed)))
            print(f"  seed {seed}:", " ".join(f"{t:2d}" for t in out[0]))

        # Weight-only int8 serving (models/quant.py): halves the per-token
        # HBM weight traffic that bounds decode latency on-chip; on this
        # well-trained tiny model the greedy continuation is unchanged.
        from jobset_tpu.models.quant import quantize_params_for_serving

        params_q = quantize_params_for_serving(params)
        int8_gen = build_generate(cfg, mesh, max_new_tokens=8, quantized=True)
        out_q = np.asarray(int8_gen(params_q, prompt))
        print("greedy with int8 weights:")
        for row in out_q:
            print("  ", " ".join(f"{t:2d}" for t in row))
        if list(out_q[0, 4:]) != expect0:
            print("int8 decode diverged from the learned pattern",
                  file=sys.stderr)
            return 1

    print("done")
    return 0


if __name__ == "__main__":
    sys.exit(main())
