#!/usr/bin/env python
"""Run a JobSet example end-to-end on the simulated cluster.

Loads a manifest, admits it (defaulting + validation webhooks), reconciles on
the in-process cluster kernel until the JobSet reaches a terminal state
(executing any training workload with the in-process runner), then prints the
resulting status as YAML — the `kubectl apply && kubectl get -o yaml`
experience against the simulator.

Usage:
    python examples/run_example.py examples/training/lm-dp.yaml
"""

from __future__ import annotations

import argparse
import os
import sys

import yaml

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("manifest", help="path to a JobSet YAML manifest")
    parser.add_argument(
        "--max-rounds",
        type=int,
        default=50,
        help="max reconcile/run rounds before giving up",
    )
    parser.add_argument(
        "--tpu",
        action="store_true",
        help="run workloads on the real TPU backend (default: CPU — TPU "
        "device init blocks indefinitely when the chip is unreachable)",
    )
    args = parser.parse_args()

    if not args.tpu:
        os.environ["JAX_PLATFORMS"] = "cpu"
        # Virtual multi-device CPU platform (same as the test suite's
        # conftest): examples with an explicit workload mesh (e.g. sp x tp)
        # need more than one device. Must be set before jax initializes.
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()
        import jax

        # Env var alone is not enough under the axon sitecustomize, which
        # force-selects the TPU backend via jax.config at interpreter start.
        jax.config.update("jax_platforms", "cpu")

    from jobset_tpu import api
    from jobset_tpu.core import make_cluster
    from jobset_tpu.runtime.runner import WorkloadRunner

    with open(args.manifest) as f:
        jobsets = api.load_all(f.read())
    if not jobsets:
        print(f"no JobSet documents in {args.manifest}", file=sys.stderr)
        return 1

    # Fresh checkpoint dirs per invocation: a stale checkpoint from a prior
    # run would make the workload resume at its final step and train nothing.
    import shutil

    for js in jobsets:
        for rjob in js.spec.replicated_jobs:
            ckpt_dir = rjob.template.spec.template.spec.workload.get("checkpoint_dir")
            if ckpt_dir and ckpt_dir.startswith("/tmp/"):
                shutil.rmtree(ckpt_dir, ignore_errors=True)

    cluster = make_cluster()
    cluster.add_topology("cloud.google.com/gke-nodepool", num_domains=8,
                         nodes_per_domain=4, capacity=16)
    # TPU multi-slice examples place one job gang per slice domain.
    cluster.add_topology("tpu.google.com/slice", num_domains=8,
                         nodes_per_domain=4, capacity=16,
                         domain_prefix="slice")
    runner = WorkloadRunner(cluster)

    for js in jobsets:
        cluster.create_jobset(js)  # admission (defaults + validation) inside
    cluster.run_until_stable()

    for _ in range(args.max_rounds):
        runner.run_pending()
        cluster.run_until_stable()
        if all(
            cluster.get_jobset(js.namespace, js.name) is None
            or cluster.get_jobset(js.namespace, js.name).status.terminal_state
            for js in jobsets
        ):
            break

    for js in jobsets:
        live = cluster.get_jobset(js.namespace, js.name)
        if live is None:
            print(f"# {js.name}: deleted (TTL)")
            continue
        print(yaml.safe_dump(api.to_dict(live, include_status=True),
                             sort_keys=False))
        state = live.status.terminal_state or "Active"
        print(f"# {live.name}: {state}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
