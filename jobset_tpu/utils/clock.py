"""Injectable clocks.

The reconcile core takes a clock so TTL/condition timing is testable with a
fake clock, mirroring the clock injection at `jobset_controller.go:56,90`.
"""

from __future__ import annotations

import time


class Clock:
    def now(self) -> float:
        return time.time()


class FakeClock(Clock):
    """Deterministic clock for tests and for the simulator's virtual time."""

    def __init__(self, start: float = 0.0):
        self._now = float(start)

    def now(self) -> float:
        return self._now

    def advance(self, seconds: float) -> None:
        self._now += float(seconds)

    def set(self, t: float) -> None:
        self._now = float(t)
