"""JAX backend selection honoring the JAX_PLATFORMS environment variable.

On hosts where a sitecustomize force-selects an accelerator backend through
`jax.config` (overriding the env var), a process that was told
`JAX_PLATFORMS=cpu` must push the config back BEFORE the backend
initializes — otherwise first jax use can block on accelerator/tunnel init.
Every process entry point (CLI, benches) calls this first.
"""

from __future__ import annotations

import os


def force_cpu_if_requested() -> bool:
    """If JAX_PLATFORMS requests cpu first, make the config agree.

    Returns True when the cpu backend was forced. Must run before any jax
    computation in the process.
    """
    platforms = [p.strip() for p in os.environ.get("JAX_PLATFORMS", "").split(",")]
    if platforms[:1] != ["cpu"]:
        return False
    import jax

    jax.config.update("jax_platforms", "cpu")
    if jax.default_backend() != "cpu":
        raise RuntimeError(
            f"JAX_PLATFORMS=cpu requested but backend is {jax.default_backend()}"
        )
    return True
