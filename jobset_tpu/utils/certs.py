"""Self-signed serving certificates for the controller REST server.

Analog of the reference's webhook cert manager
(`pkg/util/cert/cert.go:43-65` + `main.go:123-127,194-219`): the reference
creates a self-signed CA, issues the webhook serving cert from it, and
gates readyz on the certs being ready. Here the controller process does the
same for its own HTTPS listener: `ensure_serving_certs(dir)` creates (or
reuses) a CA plus a server certificate under the directory, and the CLI's
`--tls-self-signed` flag wires them into the server before it starts
serving — so, like the reference, nothing listens until certs exist.

Rotation: certificates are reissued when within `rotate_before` of expiry
(the cert-controller rotator's behavior, simplified to process-start-time
rotation: the controller is restarted by its supervisor, which is when a
fresh cert matters).
"""

from __future__ import annotations

import datetime
import ipaddress
import os
from typing import Optional

CA_CERT = "ca.crt"
CA_KEY = "ca.key"
TLS_CERT = "tls.crt"
TLS_KEY = "tls.key"


def _write_private(path: str, data: bytes) -> None:
    fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
    with os.fdopen(fd, "wb") as f:
        f.write(data)


def ensure_serving_certs(
    cert_dir: str,
    hosts: Optional[list[str]] = None,
    valid_days: int = 365,
    rotate_before: datetime.timedelta = datetime.timedelta(days=30),
) -> tuple[str, str, str]:
    """Create or reuse a self-signed CA + server cert under `cert_dir`.

    Returns (ca_cert_path, server_cert_path, server_key_path). Existing,
    still-valid certificates are reused so restarts keep client trust; a
    cert within `rotate_before` of expiry is reissued from the same CA.
    """
    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import rsa
    from cryptography.x509.oid import NameOID

    os.makedirs(cert_dir, exist_ok=True)
    ca_cert_path = os.path.join(cert_dir, CA_CERT)
    ca_key_path = os.path.join(cert_dir, CA_KEY)
    crt_path = os.path.join(cert_dir, TLS_CERT)
    key_path = os.path.join(cert_dir, TLS_KEY)
    hosts = hosts or ["localhost", "127.0.0.1"]
    now = datetime.datetime.now(datetime.timezone.utc)

    def _still_valid(path: str, required_hosts: Optional[list[str]] = None) -> bool:
        if not os.path.exists(path):
            return False
        try:
            cert = x509.load_pem_x509_certificate(open(path, "rb").read())
        except ValueError:
            return False
        if cert.not_valid_after_utc - rotate_before <= now:
            return False
        if required_hosts:
            # Reuse only if the existing leaf already names every requested
            # host — a controller restarted on a new address must get a
            # fresh cert, not an 11-month hostname-mismatch.
            try:
                sans = cert.extensions.get_extension_for_class(
                    x509.SubjectAlternativeName
                ).value
            except x509.ExtensionNotFound:
                return False
            named = {str(v) for v in sans.get_values_for_type(x509.DNSName)}
            named |= {
                str(v) for v in sans.get_values_for_type(x509.IPAddress)
            }
            if not set(required_hosts) <= named:
                return False
        return True

    # CA: reuse while valid, else mint a fresh one (and with it, the chain).
    if _still_valid(ca_cert_path) and os.path.exists(ca_key_path):
        ca_key = serialization.load_pem_private_key(
            open(ca_key_path, "rb").read(), password=None
        )
        ca_cert = x509.load_pem_x509_certificate(open(ca_cert_path, "rb").read())
    else:
        ca_key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
        ca_name = x509.Name(
            [x509.NameAttribute(NameOID.COMMON_NAME, "jobset-tpu-ca")]
        )
        ca_cert = (
            x509.CertificateBuilder()
            .subject_name(ca_name)
            .issuer_name(ca_name)
            .public_key(ca_key.public_key())
            .serial_number(x509.random_serial_number())
            .not_valid_before(now - datetime.timedelta(minutes=5))
            .not_valid_after(now + datetime.timedelta(days=valid_days))
            .add_extension(x509.BasicConstraints(ca=True, path_length=0), True)
            .sign(ca_key, hashes.SHA256())
        )
        open(ca_cert_path, "wb").write(
            ca_cert.public_bytes(serialization.Encoding.PEM)
        )
        _write_private(
            ca_key_path,
            ca_key.private_bytes(
                serialization.Encoding.PEM,
                serialization.PrivateFormat.TraditionalOpenSSL,
                serialization.NoEncryption(),
            ),
        )
        # New CA invalidates any existing leaf.
        for stale in (crt_path, key_path):
            if os.path.exists(stale):
                os.unlink(stale)

    if not _still_valid(crt_path, required_hosts=hosts):
        key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
        sans = []
        for host in hosts:
            try:
                sans.append(x509.IPAddress(ipaddress.ip_address(host)))
            except ValueError:
                sans.append(x509.DNSName(host))
        # A leaf must never outlive its CA: a reused late-life CA would
        # otherwise sign a chain that breaks mid-leaf-validity.
        leaf_expiry = min(
            now + datetime.timedelta(days=valid_days),
            ca_cert.not_valid_after_utc,
        )
        cert = (
            x509.CertificateBuilder()
            .subject_name(
                x509.Name(
                    [x509.NameAttribute(NameOID.COMMON_NAME, "jobset-tpu-controller")]
                )
            )
            .issuer_name(ca_cert.subject)
            .public_key(key.public_key())
            .serial_number(x509.random_serial_number())
            .not_valid_before(now - datetime.timedelta(minutes=5))
            .not_valid_after(leaf_expiry)
            .add_extension(x509.SubjectAlternativeName(sans), False)
            .sign(ca_key, hashes.SHA256())
        )
        open(crt_path, "wb").write(cert.public_bytes(serialization.Encoding.PEM))
        _write_private(
            key_path,
            key.private_bytes(
                serialization.Encoding.PEM,
                serialization.PrivateFormat.TraditionalOpenSSL,
                serialization.NoEncryption(),
            ),
        )
    return ca_cert_path, crt_path, key_path
