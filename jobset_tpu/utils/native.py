"""Build-on-first-use loader for the C++ native helpers.

The reference ships a compiled (Go) runtime; our compiled surface is the
data-feed hot path (`jobset_tpu/native/*.cpp`). Rather than requiring a
build step at install time (the environment may have no toolchain), the
shared object is compiled lazily with g++ into a per-source-hash cache
under ``$JOBSET_TPU_NATIVE_CACHE`` (default: alongside the source when
writable, else a temp-dir cache), and every caller degrades gracefully to
its pure-numpy implementation when compilation or loading fails.

``JOBSET_TPU_NO_NATIVE=1`` disables the native path outright (tests use it
to pin the fallback).
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import tempfile
from typing import Optional

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(__file__)), "native")
_UNSET = object()
_DATALOADER: object = _UNSET


def _trusted_dir(path: str) -> bool:
    """Only load/compile shared objects from a directory we own that is
    not writable by group/other (a predictable /tmp path could otherwise
    be pre-created by another local user to plant a library)."""
    try:
        st = os.stat(path)
    except OSError:
        return False
    return st.st_uid == os.getuid() and not (st.st_mode & 0o022)


def _build(src_path: str) -> Optional[str]:
    """Compile src to a cached .so; returns the path or None."""
    with open(src_path, "rb") as f:
        digest = hashlib.sha256(f.read()).hexdigest()[:16]
    name = os.path.basename(src_path).rsplit(".", 1)[0]
    candidates = []
    env_cache = os.environ.get("JOBSET_TPU_NATIVE_CACHE")
    if env_cache:
        candidates.append(env_cache)
    candidates.append(_NATIVE_DIR)
    candidates.append(
        os.path.join(tempfile.gettempdir(), f"jobset_tpu_native_{os.getuid()}")
    )
    for cache in candidates:
        so_path = os.path.join(cache, f"_{name}_{digest}.so")
        if os.path.exists(so_path) and _trusted_dir(cache):
            return so_path
        tmp = so_path + f".tmp{os.getpid()}"
        try:
            os.makedirs(cache, mode=0o700, exist_ok=True)
            if not _trusted_dir(cache):
                # A pre-existing cache dir we don't own (or one writable by
                # others) could serve a planted .so straight into
                # ctypes.CDLL — never build into or load from it.
                continue
            subprocess.run(
                ["g++", "-O3", "-shared", "-fPIC", "-o", tmp, src_path],
                check=True,
                capture_output=True,
                timeout=120,
            )
            os.replace(tmp, so_path)  # atomic: concurrent builders race safely
            return so_path
        except (OSError, subprocess.SubprocessError):
            try:
                os.unlink(tmp)  # a failed/timed-out build must not litter
            except OSError:
                pass
            continue
    return None


def dataloader_lib():
    """The dataloader shared library, or None (numpy fallback)."""
    global _DATALOADER
    if _DATALOADER is not _UNSET:
        return _DATALOADER
    if os.environ.get("JOBSET_TPU_NO_NATIVE"):
        _DATALOADER = None
        return None
    try:
        so = _build(os.path.join(_NATIVE_DIR, "dataloader.cpp"))
        if so is None:
            _DATALOADER = None
            return None
        lib = ctypes.CDLL(so)
        fn = lib.gather_windows_u16_i32
        fn.restype = ctypes.c_int32
        fn.argtypes = [
            ctypes.c_void_p,  # tokens (u16*)
            ctypes.c_void_p,  # starts (i64*)
            ctypes.c_int64,   # n_rows
            ctypes.c_int64,   # window
            ctypes.c_void_p,  # inputs out (i32*)
            ctypes.c_void_p,  # targets out (i32*)
        ]
        _DATALOADER = lib
    except OSError:
        _DATALOADER = None
    return _DATALOADER


def gather_windows(tokens, starts, seq_len: int):
    """Fused native gather: (inputs, targets) int32 [n, seq_len] plus the
    max token id, from a uint16 token array. Returns None when the native
    library is unavailable or the dtype is not uint16 (callers fall back
    to numpy)."""
    import numpy as np

    lib = dataloader_lib()
    if lib is None or tokens.dtype != np.uint16:
        return None
    starts = np.ascontiguousarray(starts, dtype=np.int64)
    n = int(starts.shape[0])
    if n == 0:
        return None
    # Bounds guard the numpy path gets for free (ragged slices make
    # np.stack raise): an out-of-range start must never reach the C
    # function, where it would be a silent OOB read.
    if int(starts.min()) < 0 or int(starts.max()) + seq_len + 1 > tokens.shape[0]:
        raise ValueError(
            f"window start out of range for corpus of {tokens.shape[0]} tokens"
        )
    inputs = np.empty((n, seq_len), np.int32)
    targets = np.empty((n, seq_len), np.int32)
    max_id = lib.gather_windows_u16_i32(
        tokens.ctypes.data,
        starts.ctypes.data,
        n,
        seq_len,
        inputs.ctypes.data,
        targets.ctypes.data,
    )
    return inputs, targets, int(max_id)
