"""Small generic helpers (analog of `pkg/util/collections/collections.go`)."""

from __future__ import annotations

from typing import Iterable, Optional, TypeVar

T = TypeVar("T")


def merge_maps(*maps: Optional[dict]) -> dict:
    """Merge left to right; later maps win on key conflicts
    (collections.go MergeMaps semantics)."""
    out: dict = {}
    for m in maps:
        if m:
            out.update(m)
    return out


def merge_slices(a: Optional[Iterable[T]], b: Optional[Iterable[T]]) -> list[T]:
    """Concatenate, dropping duplicates from `b` already present in `a`."""
    out: list[T] = list(a or [])
    for item in b or []:
        if item not in out:
            out.append(item)
    return out


def capped_exponential_backoff(
    failures: int, base_s: float, cap_s: float
) -> float:
    """`base * 2^(n-1)`, capped — the workqueue
    ItemExponentialFailureRateLimiter curve shared by reconcile-error
    containment (core/cluster.py) and queue requeue backoff
    (queue/manager.py)."""
    return min(base_s * (2 ** (failures - 1)), cap_s)
