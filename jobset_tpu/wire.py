"""Binary wire plane: content-negotiated frame codec for the HTTP path.

The apiserver's default exchange format is JSON (YAML accepted on
manifest bodies), which every existing client keeps using untouched.
This module adds the opt-in fast path (docs/protocol.md): a client that
sends ``Content-Type: application/vnd.jobset.binary`` ships its request
body as a *wire frame*, and one that sends the same media type in
``Accept`` gets its response framed the same way.

A frame reuses the store's proven framing discipline
(``store/wal.py``: length + CRC32 + canonical JSON payload) with a
negotiation header in front::

    +-------+---------+---------+----------------+----------------+---------+
    | magic | version | kind id | length (u32 LE)| crc32 (u32 LE) | payload |
    | 2B JW |  u8     |  u8     |                |                | length  |
    +-------+---------+---------+----------------+----------------+---------+

The payload is the *canonical JSON* encoding (``store/codec.canonical``:
sorted keys, no whitespace) of exactly the same document the JSON path
carries — so the two encodings are interchangeable object-for-object,
and the store codecs' fixed point (``encode(decode(encode(x))) ==
encode(x)``, tests/test_store.py) extends to the wire: a manifest that
round-trips the JSON path round-trips the binary path byte-identically.
The CRC makes a truncated or corrupted body a loud 400 instead of a
silently mis-parsed manifest.

The *kind id* byte exposes the store codec registry as a wire schema
(``schema()``): id 0 is the generic API document (requests, responses,
lists, watch frames — anything the JSON path would carry), ids >= 1 name
the per-kind store codecs in sorted registry order. Generic frames are
all the HTTP path needs; the per-kind ids exist so schema-aware tooling
(the ``bench.py --wire`` microbench, future replication transports) can
tag payloads without a side channel. An unknown *version* byte is
rejected — the version is the compatibility contract, negotiated
implicitly by the media type (v1 is the only version this tree speaks).

Watch-frame delta compression (``delta``/``apply_delta``) also lives
here: coalesced watch responses carry later events for an object a
frame has already shipped as sparse set/del operations against the
in-frame predecessor instead of a full re-serialization
(docs/protocol.md "Coalesced watch frames").
"""

from __future__ import annotations

import json
import struct
import zlib
from typing import Optional

# The negotiated media type (request AND response side).
CONTENT_TYPE = "application/vnd.jobset.binary"

# Wire format version: bumped on any frame-layout or payload-contract
# change; a decoder that sees a version it does not speak must reject
# the frame (never guess).
VERSION = 1

MAGIC = b"JW"
_HEADER = struct.Struct("<II")  # (payload length, payload crc32)
_PREFIX_LEN = len(MAGIC) + 2  # magic + version + kind id

# Generic API document (the only kind id the HTTP path itself uses).
KIND_OBJECT = 0

# Batched-verb path suffixes (AIP custom-verb style): POST
# .../jobsets:batchCreate and .../jobsets:batchStatus. Shared protocol
# constant — the server's router, the flow classifier, and the client
# SDK all derive from it.
BATCH_SUFFIXES = (":batchCreate", ":batchStatus")


class WireError(ValueError):
    """Malformed, truncated, corrupt, or wrong-version wire frame."""


def _canonical(obj) -> bytes:
    # store/codec.canonical's encoding (sorted keys, no whitespace),
    # inlined bytes-side so client-side encoding does not import the
    # store plane (and its numpy dependencies) into the stdlib-light SDK.
    return json.dumps(obj, sort_keys=True, separators=(",", ":")).encode()


def kind_ids() -> dict[str, int]:
    """The wire schema's kind registry: store codec kinds in sorted
    order, ids starting at 1 (0 is the generic API document). Lazy
    import: the SDK encodes generic frames without pulling the store
    plane in."""
    from .store.codec import CODECS

    ids = {"object": KIND_OBJECT}
    for i, kind in enumerate(sorted(CODECS), start=1):
        ids[kind] = i
    return ids


def schema() -> dict:
    """Machine-readable wire schema (served at ``GET /debug/wire``):
    version byte, media type, frame layout, and the kind-id registry."""
    return {
        "version": VERSION,
        "contentType": CONTENT_TYPE,
        "frame": {
            "magic": MAGIC.decode(),
            "layout": "magic(2) version(u8) kind(u8) length(u32le) "
                      "crc32(u32le) payload(canonical JSON, length bytes)",
        },
        "kinds": kind_ids(),
    }


def encode(obj, kind_id: int = KIND_OBJECT) -> bytes:
    """Python document -> one wire frame."""
    payload = _canonical(obj)
    return b"".join((
        MAGIC,
        bytes((VERSION, kind_id)),
        _HEADER.pack(len(payload), zlib.crc32(payload)),
        payload,
    ))


def decode_frame(data: bytes) -> tuple[object, int]:
    """One wire frame -> (document, kind id). Raises WireError on a bad
    magic, unknown version, short frame, CRC mismatch, trailing junk, or
    a payload that is not valid JSON."""
    if len(data) < _PREFIX_LEN + _HEADER.size:
        raise WireError("wire frame shorter than its header")
    if data[: len(MAGIC)] != MAGIC:
        raise WireError("bad wire frame magic (not a binary frame?)")
    version, kind_id = data[len(MAGIC)], data[len(MAGIC) + 1]
    if version != VERSION:
        raise WireError(
            f"unsupported wire version {version} (this server speaks "
            f"{VERSION}); fall back to application/json"
        )
    length, crc = _HEADER.unpack_from(data, _PREFIX_LEN)
    start = _PREFIX_LEN + _HEADER.size
    payload = data[start : start + length]
    if len(payload) != length:
        raise WireError(
            f"truncated wire frame: want {length} payload bytes, "
            f"got {len(payload)}"
        )
    if len(data) != start + length:
        raise WireError("trailing bytes after wire frame")
    if zlib.crc32(payload) != crc:
        raise WireError("wire frame CRC mismatch (corrupt payload)")
    try:
        return json.loads(payload), kind_id
    except json.JSONDecodeError as exc:
        raise WireError(
            f"wire frame payload is not valid JSON: {exc}"
        ) from exc


def decode(data: bytes):
    """One wire frame -> document (kind id discarded)."""
    return decode_frame(data)[0]


def peek_payload(data: bytes, limit: int = 4096) -> bytes:
    """The first `limit` payload bytes of a frame WITHOUT validating it
    (no CRC, no length check) — for cheap pre-admission classification
    peeks only (the payload is canonical JSON text, so byte-level regex
    peeks like the flow plane's spec.priority scan work on it). Returns
    b"" for anything too short to be a frame."""
    start = _PREFIX_LEN + _HEADER.size
    if len(data) <= start or data[: len(MAGIC)] != MAGIC:
        return b""
    return data[start : start + limit]


# ---------------------------------------------------------------------------
# Content negotiation
# ---------------------------------------------------------------------------


def is_binary_content_type(content_type: Optional[str]) -> bool:
    return bool(content_type) and content_type.split(";")[0].strip() == (
        CONTENT_TYPE
    )


def accepts_binary(accept: Optional[str]) -> bool:
    """Whether an Accept header asks for the binary encoding. Exact
    media-type match only: ``*/*`` and ``application/*`` keep getting
    JSON — a generic client must never receive frames it cannot parse."""
    if not accept:
        return False
    return any(
        part.split(";")[0].strip() == CONTENT_TYPE
        for part in accept.split(",")
    )


def negotiate(headers: Optional[dict]) -> tuple[bool, bool]:
    """(request body is binary, response should be binary) from the
    request headers — a pure function of Content-Type/Accept with no
    side effects, so it may run before flow admission (a shed 429 must
    still honor the client's Accept without having touched anything)."""
    headers = headers or {}
    return (
        is_binary_content_type(headers.get("content-type")),
        accepts_binary(headers.get("accept")),
    )


# ---------------------------------------------------------------------------
# Watch-frame delta compression
# ---------------------------------------------------------------------------
#
# Ops are flat [op, path, value?] triples over RFC 6901 pointer paths:
# ["set", "/status/replicatedJobsStatus/0", {...}] assigns (creating the
# key), ["del", "/metadata/labels/stale"] removes. Dicts recurse;
# lists are replaced wholesale when unequal (watch diffs overwhelmingly
# touch scalar status fields — element-wise list diffs don't pay for
# their decode complexity on this wire).


def _escape(token: str) -> str:
    return token.replace("~", "~0").replace("/", "~1")


def _unescape(token: str) -> str:
    return token.replace("~1", "/").replace("~0", "~")


def delta(old, new, path: str = "") -> list:
    """Sparse ops transforming `old` into `new`; [] when equal."""
    if isinstance(old, dict) and isinstance(new, dict):
        ops: list = []
        for key, value in new.items():
            sub = f"{path}/{_escape(str(key))}"
            if key not in old:
                ops.append(["set", sub, value])
            else:
                ops.extend(delta(old[key], value, sub))
        for key in old:
            if key not in new:
                ops.append(["del", f"{path}/{_escape(str(key))}"])
        return ops
    if old != new:
        return [["set", path, new]]
    return []


def apply_delta(old, ops: list):
    """Replay `ops` (from :func:`delta`) onto a deep copy of `old`."""
    import copy

    doc = copy.deepcopy(old)
    for op in ops:
        name, path = op[0], op[1]
        tokens = [_unescape(t) for t in path.split("/")[1:]]
        if not tokens:
            if name != "set":
                raise WireError("cannot delete the document root")
            doc = copy.deepcopy(op[2])
            continue
        parent = doc
        for token in tokens[:-1]:
            parent = parent[token]
        if name == "set":
            parent[tokens[-1]] = copy.deepcopy(op[2])
        elif name == "del":
            parent.pop(tokens[-1], None)
        else:
            raise WireError(f"unknown delta op {name!r}")
    return doc
