"""Deterministic keyspace partitioner: JobSet key -> shard.

The map is a pure function of ``(seed, shards)``: ``shard_for`` hashes
``namespace/name`` with a keyed blake2b digest (the same stable-hash
discipline the flow plane's shuffle-sharding uses) and reduces modulo the
shard count — no coordination, no lookup table, every router and every
shard member computes the same owner independently. ``epoch`` increments
on every re-partition (a split/merge that changes the shard count or the
key->shard function), which is what lets the front door 410 any watch
position minted before the split: a resume token must never silently
straddle two journals (docs/sharding.md).

Persistence rides the store's atomic snapshot-write ritual
(``store.write_snapshot_file``: tmp + fsync + rename + dir fsync) into
``shardmap.json`` next to the shard groups' data dirs, so a restarted
front door recovers the exact partition (and epoch) it was serving.
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Optional

MAP_FILE = "shardmap.json"


class ShardMap:
    """Immutable-by-convention partition descriptor.

    ``homes`` (shard -> region) and ``addresses`` (shard -> advertised
    ``scheme://host:port`` route of the group's serving surface) are
    placement/runtime annotations carried for hints and ``/debug/shards``;
    routing itself depends only on (seed, shards).
    """

    def __init__(self, shards: int, seed: int = 0, epoch: int = 1,
                 homes: Optional[dict] = None,
                 addresses: Optional[dict] = None):
        if shards < 1:
            raise ValueError(f"shard count must be >= 1, got {shards}")
        self.shards = int(shards)
        self.seed = int(seed)
        self.epoch = int(epoch)
        self.homes: dict[int, str] = {
            int(k): v for k, v in (homes or {}).items()
        }
        self.addresses: dict[int, str] = {
            int(k): v for k, v in (addresses or {}).items()
        }

    # -- the partition function ---------------------------------------------

    def shard_for(self, namespace: str, name: str) -> int:
        """Owning shard of ``namespace/name``: keyed blake2b of the full
        key, reduced modulo the shard count. Stable across processes and
        Python versions (hashlib, never the salted builtin hash)."""
        digest = hashlib.blake2b(
            f"{namespace}/{name}".encode(),
            digest_size=8,
            key=f"shardmap-{self.seed}".encode(),
        ).digest()
        return int.from_bytes(digest, "big") % self.shards

    def key_for_shard(self, shard: int, index: int,
                      namespace: str = "default",
                      prefix: str = "k") -> str:
        """Deterministic probe for a name that hashes to ``shard`` (tests
        and the bench pre-bucket their write keys per shard with this):
        walks ``{prefix}-{index}-{n}`` until the digest lands."""
        n = 0
        while True:
            name = f"{prefix}-{index:04d}-{n}"
            if self.shard_for(namespace, name) == shard:
                return name
            n += 1

    # -- runtime annotations -------------------------------------------------

    def address_of(self, shard: int) -> str:
        """Advertised full route (``scheme://host:port``) of the shard
        group's serving surface — what misroute hints carry so a client
        can actually follow them ("" when the plane never annotated)."""
        return self.addresses.get(int(shard), "")

    def resplit(self, shards: int) -> "ShardMap":
        """New map over ``shards`` partitions at epoch+1 — the split/merge
        migration input. Homes/addresses do NOT carry over: the new
        partition re-solves placement and re-annotates."""
        return ShardMap(shards, seed=self.seed, epoch=self.epoch + 1)

    # -- wire / persistence --------------------------------------------------

    def to_dict(self) -> dict:
        return {
            "shards": self.shards,
            "seed": self.seed,
            "epoch": self.epoch,
            "homes": {str(k): v for k, v in sorted(self.homes.items())},
            "addresses": {
                str(k): v for k, v in sorted(self.addresses.items())
            },
        }

    @classmethod
    def from_dict(cls, doc: dict) -> "ShardMap":
        return cls(
            int(doc["shards"]),
            seed=int(doc.get("seed", 0)),
            epoch=int(doc.get("epoch", 1)),
            homes=doc.get("homes") or {},
            addresses=doc.get("addresses") or {},
        )

    def persist(self, base_dir: str) -> str:
        """Durably write the map (store's atomic snapshot ritual, under
        the MAP_FILE name) so a restarted front door serves the exact
        partition + epoch it crashed with."""
        from ..store.store import write_snapshot_file

        write_snapshot_file(base_dir, self.to_dict(), filename=MAP_FILE)
        return os.path.join(base_dir, MAP_FILE)

    @classmethod
    def load(cls, base_dir: str) -> Optional["ShardMap"]:
        path = os.path.join(base_dir, MAP_FILE)
        try:
            with open(path) as f:
                return cls.from_dict(json.load(f))
        except (OSError, ValueError, KeyError):
            return None


__all__ = ["MAP_FILE", "ShardMap"]
