"""Routing front door core: per-key dispatch + merged watch journal.

``ShardRouter`` is the piece ``ControllerServer`` consults when it is
constructed as a front door (``shard_router=``, docs/sharding.md): the
flow plane has already classified/admitted the request; the router then

* resolves the owning shard of a ``namespace/name`` key through the
  :class:`ShardMap` and **dispatches** to that shard group's current
  leader server (in-process ``_route`` call — the same request pipeline
  a direct client would hit: the shard's own fences, replication
  quorum, Warning semantics all apply). Every dispatch is one delivery
  over the network fault model's directed ``(front-door, leader)`` link
  and one arrival at the ``shard.route`` chaos point, so region cuts
  and injected routing faults degrade exactly the shards they name;
* answers **503 + shard-leader hint** (Retry-After paced like every
  other fence) when the owning shard has no reachable leader — the
  client retries or follows the hint to the shard's own surface;
* serves **cross-shard LISTs** by fanning out to every shard and
  merging (a shard that cannot answer fails the list: a merged list
  silently missing a shard would read as mass deletion to an informer);
* maintains the **merged watch journal**: per-shard cursors pull each
  shard's journal — bounded by that shard's quorum delivery floor, so
  un-quorum-committed events never cross the front door — and append
  into one router-rv-ordered journal that cross-shard watchers
  long-poll. Jobsets always merge; child kinds (jobs/pods/services)
  join on first front-door list/watch (``activate_kind``) and are
  re-activated on every shard leader at each ingest, so a replica
  migration's new leader keeps journaling them. Router rvs are what
  cross-shard session monotonicity is checked over
  (``verify.check_sharded_history``).

Re-partitioning (``resplit``) swaps the map at a new epoch and marks the
whole journal trimmed: every pre-split resume token answers 410 and the
watcher relists into the owning shards' post-migration state — a watch
may never silently straddle two journals.
"""

from __future__ import annotations

import threading
from typing import Optional

from ..core import metrics
from .map import ShardMap
from .topology import FRONT_DOOR_SRC

# Bound on the merged journal (same order as the per-shard journals).
ROUTER_JOURNAL_LIMIT = 4096


class ShardHandle:
    """One shard group as the router sees it: id, serving address, and a
    live leader resolver. ``group`` is anything with ``.leader()``
    returning an object carrying ``replica_id`` and ``server`` (the
    in-process ``ha.ReplicaSet`` shape), or None while leaderless."""

    def __init__(self, shard_id: int, group, address: str = ""):
        self.shard_id = int(shard_id)
        self.group = group
        self.address = address

    def leader(self):
        """(replica_id, server) of the current leader, or (None, None)."""
        replica = self.group.leader()
        if replica is None or replica.server is None:
            return None, None
        return replica.replica_id, replica.server


class ShardRouter:
    """Key->shard dispatch plus the merged cross-shard journal."""

    def __init__(self, shard_map: ShardMap, handles: list[ShardHandle],
                 src: str = FRONT_DOOR_SRC, injector=None):
        self.map = shard_map
        self.handles: dict[int, ShardHandle] = {
            h.shard_id: h for h in handles
        }
        self.src = src
        self.injector = injector
        # Serializes whole ingest passes (snapshot cursors -> pull shard
        # journals -> append): concurrent pulls over the same cursors
        # would merge every shard event twice. Ordered BEFORE
        # _journal_lock (never acquired while holding it).
        self._ingest_lock = threading.Lock()
        # Re-partition write fence: while set, mutating dispatches answer
        # 503 + Retry-After — a write landing on an old owner AFTER its
        # manifests were snapshotted for migration would be stranded
        # across the map swap (acked but never migrated). Reads/lists
        # keep serving throughout. The in-flight counter closes the
        # check-to-dispatch TOCTOU: fence_writes(True) DRAINS writers
        # already past the check before the caller may snapshot.
        self._write_fence = threading.Event()  # guarded-by: _flight_lock
        self._flight_lock = threading.Condition()
        self._inflight_writes = 0  # guarded-by: _flight_lock
        # Merged-journal state, all guarded by this condition (router
        # rvs, the event list, per-shard pull cursors, the trim floor).
        # Events carry their kind: child kinds (jobs/pods/services)
        # merge into the SAME router-rv-ordered journal as jobsets once
        # activated, so one cursor per shard covers every kind.
        self._journal_lock = threading.Condition()
        self._events: list[tuple[int, str, str, dict]] = []  # guarded-by: _journal_lock
        self._rv = 0  # guarded-by: _journal_lock
        self._trimmed_rv = 0  # guarded-by: _journal_lock
        self._cursors: dict[int, int] = {}  # guarded-by: _journal_lock
        # Kinds the merged journal carries. Child kinds join on first
        # front-door list/watch (activate_kind) and are re-activated on
        # every shard leader at each ingest — a replica-migration or
        # failover hands the shard to a leader that has never journaled
        # them.
        self._kinds: set[str] = {"jobsets"}  # guarded-by: _journal_lock
        # Last leader seen per shard. While child kinds are merged, a
        # leader change trims the whole journal: the new leader only
        # journals child deltas from its activation on, so a watcher
        # resuming across the gap could go silently stale — 410/relist
        # is the honest answer (informer level-triggered contract).
        self._leaders: dict[int, Optional[str]] = {}  # guarded-by: _journal_lock
        # Latest placement re-solve output (plane.resolve_placement):
        # where the homes WOULD move given the current fault set.
        self._planned_homes: dict[int, str] = {}  # guarded-by: _journal_lock
        # The plane's MigrationController (set by ShardedControlPlane):
        # /debug/migrations serves its describe() through the front
        # door.
        self.migrations = None
        metrics.shard_count.set(self.map.shards)

    def fence_writes(self, fenced: bool, drain_timeout_s: float = 30.0):
        """Raise/lower the re-partition write fence (plane.resplit's
        migration window). Raising it BLOCKS until every in-flight
        mutating dispatch has completed: a writer that passed the fence
        check before it was set must land (and be visible to the
        migration's manifest snapshots) before this returns — otherwise
        its clean-acked object could be stranded on an old owner."""
        import time as _t

        if not fenced:
            with self._flight_lock:
                self._write_fence.clear()
            return
        deadline = _t.monotonic() + drain_timeout_s
        with self._flight_lock:
            self._write_fence.set()
            while self._inflight_writes > 0:
                remaining = deadline - _t.monotonic()
                if remaining <= 0:
                    self._write_fence.clear()
                    raise RuntimeError(
                        f"{self._inflight_writes} in-flight write(s) "
                        f"never drained within {drain_timeout_s}s; "
                        f"write fence aborted"
                    )
                self._flight_lock.wait(remaining)

    def active_shards(self) -> list[int]:
        """Shard ids the CURRENT map can route to: provisioned-but-idle
        groups past the map's shard count hold no objects and must not
        fail cross-shard lists or cost journal pulls."""
        return [s for s in sorted(self.handles) if s < self.map.shards]

    def set_planned_homes(self, planned: dict[int, str]) -> None:
        """Record the latest shard-home re-solve (surfaced at
        /debug/shards as `plannedHomes`)."""
        with self._journal_lock:
            self._planned_homes = dict(planned)

    def activate_kind(self, kind: str) -> None:
        """Admit a child kind (jobs/pods/services) into the merged
        journal: activate its shard-side journaling on every current
        leader, then start carrying its events under router rvs. Called
        from the front door's child list AND watch paths — activating
        at list time is what closes the list-then-watch gap (events
        landing between the two merge under rvs ABOVE the list's
        token, so the watch re-delivers instead of missing them)."""
        with self._journal_lock:
            if kind in self._kinds:
                return
            self._kinds.add(kind)
        for shard in self.active_shards():
            _leader_id, server = self.handles[shard].leader()
            if server is not None:
                server._activate_watch_kind(kind)

    # -- key routing ---------------------------------------------------------

    def shard_for(self, namespace: str, name: str) -> int:
        return self.map.shard_for(namespace, name)

    def hint(self, shard: int) -> dict:
        """The shard-leader hint misroute/unroutable answers carry: shard
        id plus the group's advertised full route."""
        handle = self.handles.get(int(shard))
        address = self.map.address_of(shard) or (
            handle.address if handle is not None else ""
        )
        return {"shard": int(shard), "leaderAddress": address or None}

    # -- dispatch ------------------------------------------------------------

    def dispatch(self, shard: int, method: str, path: str, body: bytes,
                 headers: Optional[dict] = None):
        """Forward one request to the owning shard's leader; returns the
        shard server's full ``_route`` response tuple with the shard id
        stamped (``X-Jobset-Shard``), or a 503 + hint when the shard is
        unroutable (no leader, link cut, chaos fault)."""
        from ..chaos import net as chaos_net
        from ..chaos.injector import consult

        mutating = method in ("POST", "PUT", "DELETE", "PATCH")
        if mutating:
            # Fence check + in-flight registration are ONE atomic step
            # under the flight lock: a writer past this point is
            # guaranteed visible to fence_writes' drain, so resplit's
            # manifest snapshots can never miss a landing write.
            with self._flight_lock:
                if self._write_fence.is_set():
                    return (
                        503,
                        {"error": "keyspace re-partition in progress; "
                                  "writes are fenced until the "
                                  "migration completes — retry"},
                        None,
                        {"Retry-After": "1"},
                    )
                self._inflight_writes += 1
        try:
            handle = self.handles.get(int(shard))
            if handle is None:
                return self._unroutable(
                    shard, f"shard {shard} is not served"
                )
            fault = consult("shard.route", f"{method} shard={shard}",
                            injector=self.injector)
            if fault is not None and fault.kind != "latency":
                return self._unroutable(
                    shard, f"chaos shard.route: injected {fault.kind} "
                           f"(seq {fault.seq})"
                )
            leader_id, server = handle.leader()
            if server is None:
                return self._unroutable(shard, "no leader elected")
            reason = chaos_net.check_link(self.src, leader_id,
                                          injector=self.injector)
            if reason is not None:
                return self._unroutable(shard, reason)
            metrics.shard_requests_total.inc(str(shard))
            result = server._route(method, path, body,
                                   headers=headers or {})
            if mutating:
                # A routed write journaled events on ITS shard only:
                # pull just that shard through so parked cross-shard
                # watchers wake immediately — a full all-shards fan-out
                # here would serialize every writer thread on the
                # ingest lock doing O(shards) journal scans per write,
                # the exact contention the sharding exists to remove
                # (watch polls still sweep every shard on their own
                # cadence).
                self.ingest(only_shard=shard)
            return self._stamp_shard(result, shard)
        finally:
            if mutating:
                with self._flight_lock:
                    self._inflight_writes -= 1
                    self._flight_lock.notify_all()

    def _unroutable(self, shard: int, reason: str):
        metrics.shard_unroutable_total.inc(str(int(shard)))
        return (
            503,
            {
                "error": (
                    f"shard {shard} is unroutable from the front door "
                    f"({reason}); retry, or follow the shard-leader hint"
                ),
                **self.hint(shard),
            },
            None,
            {"Retry-After": "1", "X-Jobset-Shard": str(int(shard))},
        )

    @staticmethod
    def _stamp_shard(result, shard: int):
        code, payload = result[0], result[1]
        ctype = result[2] if len(result) > 2 else None
        extra = dict(result[3]) if len(result) > 3 else {}
        extra.setdefault("X-Jobset-Shard", str(int(shard)))
        return (code, payload, ctype, extra)

    # -- cross-shard list ----------------------------------------------------

    def merged_list(self, method_path: str, headers: Optional[dict] = None,
                    items_key: str = "items"):
        """Fan a GET out to every shard's leader and merge the item lists
        (sorted by (namespace, name) for a deterministic wire order).
        Any unroutable or failing shard fails the WHOLE list with its
        hint: a partial merged list would read as mass deletion to a
        relisting informer.

        The merged resourceVersion is the router journal head captured
        BEFORE the per-shard GETs: a write landing mid-fan-out then
        appears in the items but not under the token, so the subsequent
        watch re-delivers it (a duplicate upsert — harmless to an
        informer). Capturing the head AFTER the GETs would invert that:
        items could MISS a write whose event the token already covers,
        and the informer would never see it — the list-then-watch gap."""
        self.ingest()
        with self._journal_lock:
            rv = self._rv
        merged: list[dict] = []
        for shard in self.active_shards():
            result = self.dispatch(shard, "GET", method_path, b"",
                                   headers=headers)
            if result[0] != 200:
                return result
            payload = result[1]
            merged.extend(payload.get(items_key) or [])
        merged.sort(key=lambda obj: (
            ((obj.get("metadata") or {}).get("namespace") or ""),
            ((obj.get("metadata") or {}).get("name") or ""),
        ))
        return 200, {items_key: merged, "resourceVersion": rv}

    # -- merged watch journal ------------------------------------------------

    def ingest(self, only_shard=None) -> int:
        """Pull each shard's new jobsets journal events (bounded by that
        shard's quorum delivery floor) and append them to the merged
        journal under fresh router rvs. Shard reads happen OUTSIDE the
        router condition (lock-order discipline: never hold `_journal_lock`
        into a shard's `_watch_cond`); the append is one locked pass.
        The WHOLE pull-then-append runs under `_ingest_lock`: writer
        handlers and watcher polls all call here concurrently, and two
        pulls snapshotting the same cursors would each fetch the same
        shard events and append them twice. `only_shard` restricts the
        pull to one shard (the write path's targeted wake-up). Returns
        the number of events merged."""
        with self._ingest_lock:
            return self._ingest_exclusive(only_shard=only_shard)

    def _ingest_exclusive(self, only_shard=None) -> int:
        pulled: list[tuple[int, bool, list]] = []
        with self._journal_lock:
            cursors = dict(self._cursors)
            kinds = set(self._kinds)
            leaders = dict(self._leaders)
        child_kinds = kinds - {"jobsets"}
        targets = (
            [int(only_shard)] if only_shard is not None
            and int(only_shard) in self.handles
            else self.active_shards()
        )
        for shard in targets:
            handle = self.handles[shard]
            leader_id, server = handle.leader()
            if server is None:
                continue
            leader_changed = (
                shard in leaders and leaders[shard] != leader_id
            )
            leaders[shard] = leader_id
            if child_kinds:
                # Idempotent re-activation on EVERY pull: a post-
                # failover or post-migration leader has never journaled
                # the merged child kinds, and the merge would silently
                # drop their deltas otherwise.
                for kind in child_kinds:
                    server._activate_watch_kind(kind)
            cursor = cursors.get(shard, 0)
            events, floor, trimmed = server.journal_tail_kinds(
                kinds, cursor
            )
            gap = cursor < trimmed and cursor > 0
            if leader_changed and child_kinds:
                # Child deltas between the handover and this activation
                # never journaled anywhere: resuming a child watcher
                # across that gap could leave it silently stale (a
                # deletion it will never hear about). Trim -> 410 ->
                # relist.
                gap = True
            pulled.append((shard, gap, [
                (kind, ns, event) for _rv, kind, ns, event in events
            ]))
            cursors[shard] = max(cursor, floor)
        merged = 0
        with self._journal_lock:
            for shard, gap, events in pulled:
                if gap:
                    # The shard's journal trimmed past our cursor: events
                    # were lost to the merge. Honest answer: declare the
                    # whole merged journal trimmed so every watcher 410s
                    # and relists — never silently skip a gap. Advance
                    # PAST the head first: a caught-up watcher holds
                    # exactly the head as its token, and `head < trimmed`
                    # is what sends it to relist (the same off-by-one
                    # resplit() guards against).
                    self._rv += 1
                    self._trimmed_rv = self._rv
                for kind, ns, event in events:
                    self._rv += 1
                    self._events.append((self._rv, kind, ns, event))
                    merged += 1
                self._cursors[shard] = cursors[shard]
            self._leaders.update(leaders)
            if len(self._events) > ROUTER_JOURNAL_LIMIT:
                trimmed_events = self._events[:-ROUTER_JOURNAL_LIMIT]
                self._trimmed_rv = trimmed_events[-1][0]
                del self._events[:-ROUTER_JOURNAL_LIMIT]
            if merged:
                self._journal_lock.notify_all()
        return merged

    def watch(self, ns: str, resource_version: int, timeout_s: float,
              park: bool = True, retry_hint: float = 1.0,
              poll_interval_s: float = 0.05, kind: str = "jobsets"):
        """Cross-shard long-poll against the merged journal — jobsets
        and activated child kinds alike — with the same 410/partial-
        batch contract as a single server's watch. The loop re-ingests
        on each wake: routed writes notify immediately; leader-pump-
        driven changes surface within the poll interval."""
        import time as _t

        if kind != "jobsets":
            self.activate_kind(kind)
        deadline = _t.monotonic() + max(0.0, min(timeout_s, 300.0))
        while True:
            self.ingest()
            with self._journal_lock:
                if resource_version < self._trimmed_rv:
                    return 410, {
                        "error": "resourceVersion predates the current "
                                 "shard journal (trimmed or re-split); "
                                 "relist",
                        "resourceVersion": self._rv,
                    }
                if resource_version > self._rv:
                    return 410, {
                        "error": "resourceVersion is ahead of this "
                                 "front door; relist",
                        "resourceVersion": self._rv,
                    }
                batch = [
                    {"resourceVersion": rv, **event}
                    for rv, event_kind, event_ns, event in self._events
                    if rv > resource_version and event_ns == ns
                    and event_kind == kind
                ]
                head = self._rv
                if batch:
                    result = {"events": batch, "resourceVersion": head}
                    if not park:
                        result["retryAfterSeconds"] = retry_hint
                    return 200, result
                if not park:
                    return 200, {
                        "events": [], "resourceVersion": head,
                        "retryAfterSeconds": retry_hint,
                    }
                remaining = deadline - _t.monotonic()
                if remaining <= 0:
                    return 200, {"events": [], "resourceVersion": head}
                self._journal_lock.wait(min(remaining, poll_interval_s))

    # -- re-partitioning -----------------------------------------------------

    def resplit(self, new_map: ShardMap) -> None:
        """Swap in a new partition epoch: the merged journal is wholly
        trimmed (every pre-split rv answers 410 -> relist into the
        post-migration owners) and cursors restart at each shard's
        current head so the new journal carries only post-split
        events."""
        # Plain LOCK-FREE reference swap: routing reads `self.map`
        # without locking (the map object is immutable by convention; a
        # reference swap is atomic), and the caller (the plane) only
        # calls resplit once migration has finished, so either map
        # routes correctly during the swap window.
        self.map = new_map
        # Under the ingest lock: an ingest pass concurrent with the trim
        # could append pre-split events (pulled with pre-split cursors)
        # AFTER the trim, leaking old-owner state past the 410 boundary
        # — waiting it out here means anything it appended is cleared
        # below.
        with self._ingest_lock:
            # Heads over the NEW map's active set (self.map was swapped
            # above): a split UP must seed cursors for newly-activated
            # groups so their post-split events merge from here on.
            heads: dict[int, int] = {}
            for shard in self.active_shards():
                _leader_id, server = self.handles[shard].leader()
                if server is not None:
                    _events, floor, _trimmed = server.journal_tail(
                        "jobsets", 1 << 62
                    )
                    heads[shard] = floor
            with self._journal_lock:
                self._events.clear()
                # Advance PAST the old head before trimming: a caught-up
                # watcher holds exactly the old head as its resume
                # token, and `head < trimmed` is what sends it to relist
                # — trimming AT the head would keep serving the
                # pre-split position.
                self._rv += 1
                self._trimmed_rv = self._rv
                for shard, head in heads.items():
                    self._cursors[shard] = head
                self._journal_lock.notify_all()
        metrics.shard_count.set(new_map.shards)

    # -- introspection -------------------------------------------------------

    def describe(self) -> dict:
        """The /debug/shards payload: map, per-shard leader/route state,
        merged-journal position."""
        shards = {}
        for shard in sorted(self.handles):
            handle = self.handles[shard]
            leader_id, server = handle.leader()
            shards[str(shard)] = {
                "home": self.map.homes.get(shard),
                "address": self.map.address_of(shard) or handle.address,
                "leader": leader_id,
                "serving": server is not None,
            }
        with self._journal_lock:
            journal = {
                "resourceVersion": self._rv,
                "trimmedResourceVersion": self._trimmed_rv,
                "cursors": {
                    str(k): v for k, v in sorted(self._cursors.items())
                },
                "kinds": sorted(self._kinds),
            }
            planned = {
                str(k): v for k, v in sorted(self._planned_homes.items())
            }
        return {
            "map": self.map.to_dict(),
            "shards": shards,
            "plannedHomes": planned,
            "journal": journal,
        }

    def federate(self, name: Optional[str] = None) -> dict:
        """Fleet telemetry view (``GET /debug/tsdb?view=fleet``): one
        current sample of every shard replica's metrics surface, each
        series stamped with ``shard``/``replica``/``role`` labels so the
        merged view joins per-replica without name collisions.

        Per replica: a synthetic ``up`` series (1 alive / 0 dead) always;
        the full registry sample for the shard leader (in-process planes
        share one registry — the leader's scrape surface IS the process
        registry, exactly what ``GET /metrics`` on that shard serves);
        and the replication-position gauges for followers, read from
        their follower logs (followers have no HTTP surface in-process —
        their positions are the telemetry they objectively own).
        ``name`` filters to one series family."""
        from ..core import metrics as core_metrics

        series: list[dict] = []

        def emit(stamp: dict, family: str, labels: dict, value) -> None:
            if name is not None and family != name:
                return
            merged = dict(labels)
            merged.update(stamp)
            series.append(
                {"name": family, "labels": merged, "value": float(value)}
            )

        for shard_id in sorted(self.handles):
            handle = self.handles[shard_id]
            replicas = getattr(handle.group, "replicas", None) or []
            for replica in replicas:
                if not getattr(replica, "alive", False):
                    role = "down"
                elif getattr(replica, "is_leader", False):
                    role = "leader"
                else:
                    role = "follower"
                stamp = {
                    "shard": str(shard_id),
                    "replica": replica.replica_id,
                    "role": role,
                }
                emit(stamp, "up", {}, 1.0 if role != "down" else 0.0)
                if role == "leader":
                    for fam, labels, value in core_metrics.sample_registry():
                        emit(stamp, fam, dict(labels), value)
                elif role == "follower":
                    log = getattr(replica, "log", None)
                    if log is not None:
                        emit(stamp, "jobset_ha_commit_seq", {},
                             log.commit_seq)
                        emit(stamp, "jobset_ha_term", {}, log.term)
        return {
            "view": "fleet",
            "shards": len(self.handles),
            "series": series,
        }


__all__ = ["ROUTER_JOURNAL_LIMIT", "ShardHandle", "ShardRouter"]
