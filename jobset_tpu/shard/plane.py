"""``ShardedControlPlane``: N quorum-replicated shard groups + front door.

The in-process deployment shape of docs/sharding.md (the analog of N
``controller --replicate`` quorums behind a routing VIP), built from the
pieces the earlier planes proved: each shard group is an
``ha.ReplicaSet`` — its own lease, quorum-replicated WAL, reconcile pump
and watch journal — whose replicas are placed across the simulated
region topology per the shard-home solve (leader + majority in the home
region, the remainder in the next region over). The front door is an
ordinary ``ControllerServer`` carrying a :class:`ShardRouter`: flow
classification, then per-key dispatch.

Region faults: ``isolate_region``/``heal_region`` translate one region
fault into the directed link cuts of ``chaos/net.py`` (every boundary
link, both directions, front door included) and re-run the placement
solve with the dark region priced out — the planned homes move off the
fault and return on heal (``jobset_shard_resolves_total``). The
robustness contract: an isolation degrades ONLY the shards quorum-homed
in that region; every other shard keeps acking majority writes.
"""

from __future__ import annotations

import os
import threading
from typing import Optional

from ..core import make_cluster, metrics
from .map import ShardMap
from .placement import solve_shard_homes
from .router import ShardHandle, ShardRouter
from .topology import FRONT_DOOR_SRC, RegionTopology


class ShardedControlPlane:
    """N in-process shard groups, one shard map, one routing front door.

    ``groups`` physical quorum groups are provisioned (default: the
    initial shard count); the map may start smaller and ``resplit`` up
    to ``groups`` later — the split migrates objects onto their new
    owners and bumps the map epoch so pre-split watch positions 410.
    """

    def __init__(
        self,
        base_dir: str,
        shards: int = 2,
        groups: Optional[int] = None,
        replicas_per_shard: int = 3,
        topology: Optional[RegionTopology] = None,
        seed: int = 0,
        injector=None,
        lease_duration: float = 0.4,
        retry_period: float = 0.1,
        tick_interval: float = 0.05,
        read_fence: bool = True,
        address: str = "127.0.0.1:0",
        flow=None,
        cluster_factory=None,
        spread_shards=(),
        auto_migrate: bool = False,
        placement_stickiness_ms: float = 0.0,
        migration_hysteresis_steps: int = 2,
    ):
        from ..ha import ReplicaSet
        from ..server import ControllerServer
        from .migrate import MigrationController

        self.base_dir = str(base_dir)
        self.groups = int(groups if groups is not None else shards)
        if shards > self.groups:
            raise ValueError(
                f"map of {shards} shards needs >= {shards} groups "
                f"(got {self.groups})"
            )
        self.injector = injector
        self.topology = topology or RegionTopology(seed=seed)
        # Self-driving migration (docs/sharding.md "Replica migration"):
        # when auto_migrate is on, every supervision step also advances
        # the MigrationController's joint-consensus walks toward the
        # latest planned homes. The stickiness discount and the
        # controller's confirmation streak are the two hysteresis layers
        # that keep flapping links from thrashing replicas. Both default
        # off/0 so static deployments behave byte-identically.
        self.auto_migrate = bool(auto_migrate)
        self.placement_stickiness_ms = float(placement_stickiness_ms)
        # Regions currently under an isolation fault — maintained by
        # isolate_region/heal_region, consumed by every re-solve and by
        # the controller's stranded-voter accounting.
        self.excluded: set[str] = set()
        # Recover the persisted partition (docs/sharding.md): a restart
        # after a resplit must route by the exact shards/epoch it was
        # serving — rebuilding at the constructor's shard count would
        # resurrect the pre-split owners and split object histories. A
        # persisted map with a different seed (or more shards than this
        # deployment provisions) is a config change, not a recovery:
        # the flags win and the stale file is overwritten below.
        recovered = ShardMap.load(self.base_dir)
        if (recovered is not None and recovered.seed == int(seed)
                and recovered.shards <= self.groups):
            self.map = ShardMap(recovered.shards, seed=seed,
                                epoch=recovered.epoch)
        else:
            self.map = ShardMap(shards, seed=seed)
        shards = self.map.shards
        # Shard-home solve over every provisioned group (idle groups get
        # homes too: a future resplit activates them in place).
        self.homes = solve_shard_homes(self.topology, self.groups)
        self.map.homes = {
            s: self.homes[s] for s in range(self.map.shards)
        }
        self.replica_region: dict[str, str] = {}
        self.shard_groups: list = []
        # Shards placed durability-first (one replica per region) instead
        # of latency-first (majority in the home region) — the other end
        # of the placement cost tradeoff. A spread shard survives any
        # single-region isolation by failing over to its out-of-region
        # majority; a home-majority shard pays no cross-region quorum
        # latency but goes dark with its home.
        self.spread_shards = frozenset(int(s) for s in spread_shards)
        majority = replicas_per_shard // 2 + 1
        for g in range(self.groups):
            home = self.homes[g]
            if g in self.spread_shards:
                regions = self._spread_regions(home, replicas_per_shard)
            else:
                regions = self._replica_regions(home, replicas_per_shard,
                                                majority)
            group = ReplicaSet(
                os.path.join(self.base_dir, f"shard-{g}"),
                n=replicas_per_shard,
                name_prefix=f"s{g}r",
                lease_duration=lease_duration,
                retry_period=retry_period,
                tick_interval=tick_interval,
                injector=injector,
                read_fence=read_fence,
                cluster_factory=cluster_factory,
                shard_id=g,
                shard_map=self.map,
            )
            for replica, region in zip(group.replicas, regions):
                self.topology.place(replica.replica_id, region)
                self.replica_region[replica.replica_id] = region
            group.start()
            self.map.addresses[g] = f"http://{group.address}"
            self.shard_groups.append(group)
        self.router = ShardRouter(
            self.map,
            [
                ShardHandle(g, group, address=f"http://{group.address}")
                for g, group in enumerate(self.shard_groups)
            ],
            src=FRONT_DOOR_SRC,
            injector=injector,
        )
        self.map.persist(self.base_dir)
        self.migrations = MigrationController(
            self, hysteresis_steps=migration_hysteresis_steps,
            injector=injector,
        )
        # /debug/migrations is served by the front door off the router.
        self.router.migrations = self.migrations
        self.front_door = ControllerServer(
            address,
            cluster=make_cluster(),
            tick_interval=tick_interval,
            injector=injector,
            flow=flow,
            shard_router=self.router,
        ).start()
        self._stop = threading.Event()
        self._supervisor: Optional[threading.Thread] = None

    def _replica_regions(self, home: str, n: int, majority: int) -> list:
        """Per-replica regions for a group homed in `home`: the quorum
        majority co-locates with the leader in the home region (every
        write's quorum round trip stays intra-region — the latency side
        of the placement tradeoff; this is exactly what makes the group
        "quorum-homed" and the region its failure domain), the remainder
        spreads over the following regions for durability."""
        regions = [home] * majority
        others = [r for r in self.topology.regions if r != home] or [home]
        for i in range(n - majority):
            regions.append(others[i % len(others)])
        return regions

    def _spread_regions(self, home: str, n: int) -> list:
        """One replica per region, leader (replica 0) in the home — the
        durability-first placement for spread shards."""
        ordered = [home] + [
            r for r in self.topology.regions if r != home
        ]
        return [ordered[i % len(ordered)] for i in range(n)]

    @property
    def address(self) -> str:
        """The front door's serving address (host:port)."""
        return self.front_door.address

    # -- supervision ---------------------------------------------------------

    def step(self) -> None:
        """One supervision round over every shard group (elections,
        demotions) — the deterministic-scenario driver; the background
        supervisor calls the same thing on a cadence. With auto_migrate
        the migration controller walks one phase per round too, so live
        writers retrying through step() are exactly what drives a shard
        out of a dark region."""
        for group in self.shard_groups:
            group.step()
        if self.auto_migrate:
            self.migrations.step()

    def start_supervisor(self, interval_s: float = 0.05) -> None:
        """Background stepping for wall-clock deployments (bench, CLI):
        failovers inside any shard group proceed without a driver."""
        if self._supervisor is not None:
            return

        def loop():
            while not self._stop.wait(interval_s):
                try:
                    self.step()
                except Exception:
                    import logging

                    logging.getLogger("jobset_tpu.shard").exception(
                        "shard supervisor step failed"
                    )

        thread = threading.Thread(target=loop, daemon=True,
                                  name="shard-supervisor")
        thread.start()
        self._supervisor = thread

    def stop(self) -> None:
        self._stop.set()
        if self._supervisor is not None:
            self._supervisor.join(timeout=5.0)
            self._supervisor = None
        self.front_door.stop()
        for group in self.shard_groups:
            group.stop()

    # -- region faults -------------------------------------------------------

    def _plan(self):
        from ..chaos import net as chaos_net

        plan = chaos_net.get_plan(self.injector)
        if plan is None:
            raise RuntimeError(
                "region faults need a PartitionPlan attached to the "
                "plane's injector (chaos/net.py)"
            )
        return plan

    def isolate_region(self, region: str, step: Optional[int] = None):
        """Cut every directed link crossing the region boundary (the
        region-isolation fault of docs/sharding.md's runbook) and
        re-solve shard placement with the region priced out."""
        plan = self._plan()
        at = plan._current_step() if step is None else int(step)
        for src, dst in self.topology.isolation_links(region):
            plan.cut(src, dst, at=at)
        plan.advance(at)
        self.excluded.add(region)
        return self.resolve_placement(excluded=set(self.excluded))

    def heal_region(self, region: str, step: Optional[int] = None):
        """Heal the region's boundary links and re-solve placement."""
        plan = self._plan()
        at = plan._current_step() if step is None else int(step)
        for src, dst in self.topology.isolation_links(region):
            plan.heal(src, dst, at=at)
        plan.advance(at)
        self.excluded.discard(region)
        return self.resolve_placement(excluded=set(self.excluded))

    def resolve_placement(self, excluded=frozenset()) -> dict[int, str]:
        """Re-run the shard-home solve against the current (possibly
        faulted) topology — "re-solved on topology change". The result
        is the PLANNED home set (replica quorums do not teleport; the
        plan is what an operator-driven or future automated migration
        would execute), surfaced at /debug/shards and counted."""
        planned = solve_shard_homes(
            self.topology, self.groups, excluded=excluded,
            current=self.homes,
            stickiness_ms=self.placement_stickiness_ms,
        )
        self.router.set_planned_homes({
            s: planned[s] for s in range(self.map.shards)
        })
        self.migrations.note_plan(
            {s: planned[s] for s in range(self.map.shards)},
            excluded=frozenset(excluded),
        )
        metrics.shard_resolves_total.inc()
        return planned

    def quorum_homed_in(self, region: str) -> list[int]:
        """Shards whose replica MAJORITY lives in `region` — the set a
        region isolation degrades (the rest must keep acking)."""
        out = []
        for g in range(self.map.shards):
            group = self.shard_groups[g]
            majority = len(group.replicas) // 2 + 1
            in_region = sum(
                1 for r in group.replicas
                if self.replica_region.get(r.replica_id) == region
            )
            if in_region >= majority:
                out.append(g)
        return out

    # -- re-partitioning (split/merge migration) -----------------------------

    def resplit(self, shards: int) -> dict:
        """Re-partition the keyspace over `shards` of the provisioned
        groups: objects whose owner changes are migrated (manifest
        re-created on the new owner, deleted from the old — status is
        reconciled afresh on the new owner, docs/sharding.md), the map
        epoch bumps, and the router journal is wholly trimmed so every
        pre-split watch position 410-relists into the owners' state."""
        from ..api import serialization

        if shards > self.groups:
            raise ValueError(
                f"cannot split to {shards} shards over {self.groups} "
                f"provisioned groups"
            )
        new_map = self.map.resplit(shards)
        moved = 0
        # Fence front-door WRITES for the whole migration window: a
        # write acked by an old owner AFTER its manifests were
        # snapshotted would be stranded across the map swap (never
        # migrated, unreachable under the new routing). Reads and
        # lists keep serving; fenced writers retry after the hint.
        self.router.fence_writes(True)
        # (old_shard, new_shard, ns, name) copies landed so far — the
        # rollback ledger for a mid-copy failure, the delete worklist on
        # success.
        copied: list[tuple[int, int, str, str]] = []
        try:
            # Lift the member misroute guards for the move window: the
            # migration is the ONE actor legitimately touching both
            # sides of a key's move (the old owner's DELETE and the new
            # owner's POST would each 421 under either map).
            for group in self.shard_groups:
                group.shard_map = None
                for replica in group.replicas:
                    if replica.server is not None:
                        replica.server.shard_map = None
            # Phase 1 — COPY: every moving object is created on its new
            # owner; nothing is deleted yet, so a failure anywhere in
            # this phase rolls back by deleting the copies and the old
            # map stays fully authoritative.
            for g in range(self.map.shards):
                leader = self.shard_groups[g].leader()
                if leader is None:
                    raise RuntimeError(
                        f"shard {g} has no leader to migrate"
                    )
                server = leader.server
                with server.lock:
                    manifests = [
                        serialization.to_dict(js)
                        for _key, js in sorted(
                            server.cluster.jobsets.items()
                        )
                    ]
                for manifest in manifests:
                    meta = manifest.get("metadata") or {}
                    ns = meta.get("namespace") or "default"
                    name = meta.get("name") or ""
                    new_owner = new_map.shard_for(ns, name)
                    if new_owner == g:
                        continue
                    target = self.shard_groups[new_owner].leader()
                    if target is None:
                        raise RuntimeError(
                            f"shard {new_owner} has no leader to "
                            f"migrate to"
                        )
                    import json as _json

                    manifest.pop("status", None)
                    path = (
                        f"{server.API_PREFIX}/namespaces/{ns}/jobsets"
                    )
                    code, payload = target.server._route(
                        "POST", path, _json.dumps(manifest).encode()
                    )[:2]
                    if code not in (201, 409):
                        raise RuntimeError(
                            f"migration of {ns}/{name} to shard "
                            f"{new_owner} failed: HTTP {code} {payload}"
                        )
                    copied.append((g, new_owner, ns, name))
            # Phase 2 — SWAP the authoritative map: every copy exists,
            # so per-key routing by the new owners is correct from here
            # (the router's own journal epoch flips LAST, below).
            new_map.homes = {s: self.homes[s] for s in range(shards)}
            new_map.addresses = {
                s: f"http://{self.shard_groups[s].address}"
                for s in range(shards)
            }
            self.map = new_map
            # The ROUTER's per-key routing flips here too (its journal
            # epoch flips at phase 4): phase 3 deletes the old-owner
            # originals, so a front-door GET routed by the old map
            # would 404 an object that lives on its new owner.
            self.router.map = new_map
            # Phase 3 — DELETE the old-owner shadows (unreachable via
            # the API under the new map, but still consuming their old
            # shard's reconcile). A failed delete is surfaced, never
            # silently dropped: the partition is already correct, the
            # shadow is garbage to retry.
            shadows: list[str] = []
            for g, _new_owner, ns, name in copied:
                old_leader = self.shard_groups[g].leader()
                path = (
                    f"{self.front_door.API_PREFIX}/namespaces/{ns}"
                    f"/jobsets/{name}"
                )
                code = (
                    old_leader.server._route("DELETE", path, b"")[0]
                    if old_leader is not None else 0
                )
                if code not in (200, 404):
                    shadows.append(f"{ns}/{name}@shard{g}")
                else:
                    moved += 1
            # Phase 4 — the router's journal epoch flips ONLY NOW, after
            # every migration-induced journal event (the copies' ADDED
            # on new owners, the shadows' DELETED on old owners) is in
            # the past: cursors reseed at the post-migration heads, so
            # the new journal carries NO migration noise. Trimming
            # before the deletes let a watcher relist at the boundary
            # and then receive the shadows' DELETED without ever having
            # seen the copies' ADDED — a cache missing the moved
            # objects until its next full resync.
            self.router.resplit(new_map)
            new_map.persist(self.base_dir)
            result = {
                "shards": shards, "epoch": new_map.epoch, "moved": moved,
            }
            if shadows:
                result["shadow_copies"] = shadows
            return result
        except BaseException:
            # Mid-copy failure (the old map is still authoritative —
            # the guard below matters: once the swap happened the copies
            # ARE the objects and must never be rolled back): delete the
            # copies already landed on new owners, best-effort — they
            # are duplicates of objects the old map still serves — so
            # the restored old partition has no shadow state.
            if self.map is not new_map:
                for _g, new_owner, ns, name in copied:
                    target = self.shard_groups[new_owner].leader()
                    if target is not None:
                        target.server._route(
                            "DELETE",
                            f"{self.front_door.API_PREFIX}/namespaces"
                            f"/{ns}/jobsets/{name}",
                            b"",
                        )
            raise
        finally:
            # ALWAYS restore the member misroute guards — to the new map
            # on success, the old map on any migration failure. A failed
            # resplit must never leave every shard accepting keys it
            # does not own (the exact split-history hazard the 421 guard
            # exists to prevent).
            current = self.map
            for group in self.shard_groups:
                group.shard_map = current
                for replica in group.replicas:
                    if replica.server is not None:
                        replica.server.shard_map = current
            self.router.fence_writes(False)


__all__ = ["ShardedControlPlane"]
