"""Sharded control plane (docs/sharding.md): horizontal write scaling.

Replication (``jobset_tpu/ha``) made ONE quorum group survive node loss;
this package partitions the keyspace into N independently-replicated
shard groups behind one routing front door, so aggregate write
throughput scales with shard count and a region fault degrades only the
shards quorum-homed in that region.

* :mod:`map` — the deterministic keyspace partitioner: ``ShardMap``
  hashes ``namespace/name`` to a shard with a stable blake2b digest,
  carries the epoch that invalidates pre-split watch positions, and
  persists atomically through the store's snapshot-write ritual.
* :mod:`topology` — the simulated region topology: named regions,
  seeded pairwise latencies, one failure domain per region, plus the
  region-isolation helper that drives ``chaos/net.py`` link cuts.
* :mod:`placement` — shard-home assignment as a solver problem
  (NL-CPS style): a shards x region-slots cost matrix (front-door
  latency + failure-domain concentration) solved through the existing
  ``placement.solver.AssignmentSolver`` machinery, re-solved on region
  cut/heal.
* :mod:`router` — the routing front door's core: per-key dispatch to
  the owning shard group's leader, cross-shard list fan-out, and a
  merged watch journal that honors each shard's quorum delivery floor.
* :mod:`plane` — ``ShardedControlPlane``: N in-process
  ``ha.ReplicaSet`` shard groups spread over the region topology, one
  front-door ``ControllerServer``.
"""

from .map import ShardMap
from .placement import solve_shard_homes
from .plane import ShardedControlPlane
from .router import ShardRouter
from .topology import RegionTopology

__all__ = [
    "RegionTopology",
    "ShardMap",
    "ShardRouter",
    "ShardedControlPlane",
    "solve_shard_homes",
]
