"""Self-driving shard migration: joint-consensus replica moves.

The shard plane's placement solve (``resolve_placement``) produces
*planned* homes; this module closes the loop ROADMAP item 2 names by
executing a planned home change as an **add-then-remove walk** over the
existing ``ReplicaSet`` machinery, one replica in flight per shard:

1. **add** — a fresh replica joins the group in the target region as a
   non-voting *learner* (``ReplicaSet.add_learner``): the leader ships it
   every frame, it never votes, never counts toward majority, never
   contends for the lease;
2. **sync** — the learner streams to the exact log position
   (``sync_learner`` returns the remaining lag; the gate is lag == 0);
3. **promote** — the learner enters the voting set via one single-change
   membership record (``promote_learner``): consecutive voting sets
   differ by one replica, so any majority of the new set intersects any
   majority of the old — quorum is provably intact at every
   interleaving, including a crash anywhere mid-walk;
4. **retire** — the victim replica leaves via the inverse single-change
   record (``retire_replica``); its store/log close, releasing the
   data-dir flock, and its region placement is forgotten.

Every move is **term-fenced**: the leader term observed when the move
began is pinned, and any step that finds a different leader term
abort-unwinds the move back to the pre-move membership (retiring the
learner — or, past promote, the just-promoted voter — is itself a
single-change, so the unwind keeps the same quorum-overlap proof).
While the group is leaderless the walk simply waits: neither fencing
nor progress fires without a leader to observe.

Walks are enqueued from the plane's re-solve trigger with **hysteresis**
(two layers: the solver's ``stickiness_ms`` discount keeps marginally-
cheaper alternatives from uprooting a settled quorum, and the
controller's confirmation streak requires the same desired home for
``hysteresis_steps`` consecutive steps before a move starts — a
flapping link resets the streak and never thrashes replicas).

A shard's walk is COMPLETE only when a replica majority lives in the
desired home region AND no voter — the leader included, moved last —
remains in an excluded (dark) region. The second clause is the
availability half of the contract: a dark-region leader can keep its
quorum through learners placed after the cut (their links were never
scheduled), yet the front door still cannot reach it; only retiring it
forces an election that lands leadership on a reachable voter.

Chaos: each step of an ACTIVE move is one arrival at the
``shard.migrate`` injection point — ``stall`` holds the walk a step,
``break`` fails the current learner-sync attempt, ``abort`` (or any
other error kind) triggers the abort-unwind.
"""

from __future__ import annotations

import threading
from typing import Optional

PHASE_ADD = "add"
PHASE_SYNC = "sync"
PHASE_PROMOTE = "promote"
PHASE_RETIRE = "retire"

# Completed/aborted move records kept for /debug/migrations.
HISTORY_LIMIT = 256


class MigrationController:
    """Executes planned shard-home changes as joint-consensus walks.

    Driven by ``step()`` from the plane's supervision cadence (the same
    deterministic driver scenarios use); fed by ``note_plan()`` from
    every placement re-solve. One replica move in flight per shard;
    one phase transition per step, so seeded chaos interleaves with
    every intermediate membership."""

    def __init__(self, plane, hysteresis_steps: int = 2,
                 max_sync_steps: int = 400, injector=None):
        self.plane = plane
        self.hysteresis_steps = max(1, int(hysteresis_steps))
        # Sync attempts before the walk gives up (a learner that cannot
        # reach log position — its stream chaos-broken every step —
        # must unwind, not hold the shard's move slot forever).
        self.max_sync_steps = max(1, int(max_sync_steps))
        self.injector = injector
        # Serializes walk advancement (step/abort): the plane's
        # background supervisor steps from its thread while a scenario
        # driver steps inline.
        self._step_lock = threading.Lock()
        # Leaf lock for the watched state below — never held across a
        # group/coordinator call (those take the supervise/cluster
        # locks; holding ours across them would order-invert against
        # describe() readers).
        self._lock = threading.Lock()
        self._desired: dict[int, str] = {}  # guarded-by: _lock
        self._excluded: frozenset = frozenset()  # guarded-by: _lock
        self._streak: dict[int, int] = {}  # guarded-by: _lock
        self._active: dict[int, dict] = {}  # guarded-by: _lock
        self._history: list[dict] = []  # guarded-by: _lock

    # -- plan intake ---------------------------------------------------------

    def note_plan(self, planned: dict[int, str], excluded=frozenset()) -> None:
        """Record the latest placement solve. A shard whose desired home
        CHANGED restarts its confirmation streak — the hysteresis that
        keeps flapping links from thrashing replicas."""
        with self._lock:
            for shard, home in planned.items():
                if self._desired.get(shard) != home:
                    self._streak[shard] = 0
                self._desired[shard] = home
            self._excluded = frozenset(excluded)

    # -- introspection -------------------------------------------------------

    def settled(self) -> bool:
        """True when no move is in flight and every shard with a known
        desired home satisfies the walk-completion rule (majority in
        the desired home, no voter in an excluded region) — the
        scenario driver's convergence gate."""
        with self._lock:
            if self._active:
                return False
            desired = dict(self._desired)
            excluded = self._excluded
        return all(
            not self._walk_needed(shard, home, excluded)
            for shard, home in desired.items()
        )

    def describe(self) -> dict:
        """/debug/migrations payload: live moves, confirmation streaks,
        and the bounded history of completed/aborted walks."""
        settled = self.settled()
        with self._lock:
            return {
                "settled": settled,
                "hysteresisSteps": self.hysteresis_steps,
                "desired": {str(k): v for k, v in sorted(
                    self._desired.items())},
                "excludedRegions": sorted(self._excluded),
                "streaks": {str(k): v for k, v in sorted(
                    self._streak.items())},
                "active": {
                    str(k): dict(m) for k, m in sorted(self._active.items())
                },
                "history": [dict(m) for m in self._history[-32:]],
            }

    # -- placement accounting ------------------------------------------------

    def _voter_regions(self, shard: int) -> dict[str, Optional[str]]:
        group = self.plane.shard_groups[shard]
        return {
            r.replica_id: self.plane.replica_region.get(r.replica_id)
            for r in group.replicas
        }

    def _walk_needed(self, shard: int, desired: str,
                     excluded: frozenset) -> bool:
        regions = self._voter_regions(shard)
        majority = len(regions) // 2 + 1
        in_target = sum(1 for reg in regions.values() if reg == desired)
        stranded = any(reg in excluded for reg in regions.values())
        return in_target < majority or stranded

    def _pick_victim(self, shard: int, desired: Optional[str],
                     excluded: frozenset) -> Optional[str]:
        """The replica this move evacuates: excluded-region voters
        first, non-leaders before the leader (the leader moves LAST so
        the group keeps a committing leader through every earlier
        step), then — with no stranded voters — the first voter outside
        the desired home (gathering the majority). Sorted ids keep
        seeded runs picking identical victims."""
        group = self.plane.shard_groups[shard]
        leader = group.leader()
        leader_id = leader.replica_id if leader is not None else None
        regions = self._voter_regions(shard)
        stranded = sorted(
            rid for rid, reg in regions.items() if reg in excluded
        )
        if stranded:
            non_leader = [rid for rid in stranded if rid != leader_id]
            return non_leader[0] if non_leader else stranded[0]
        outside = sorted(
            rid for rid, reg in regions.items()
            if reg != desired and rid != leader_id
        )
        if outside:
            return outside[0]
        return leader_id if regions.get(leader_id) != desired else None

    def _pick_target_region(self, shard: int, desired: str,
                            excluded: frozenset) -> str:
        """Where this move's learner lands: the desired home while the
        majority is still being gathered; afterwards (evacuating
        stragglers) the first healthy non-home region, preserving the
        out-of-region durability replica."""
        regions = self._voter_regions(shard)
        majority = len(regions) // 2 + 1
        in_target = sum(1 for reg in regions.values() if reg == desired)
        if in_target < majority:
            return desired
        for region in self.plane.topology.regions:
            if region not in excluded and region != desired:
                return region
        return desired

    # -- the walk ------------------------------------------------------------

    def step(self) -> None:
        """One controller round: advance every active move by at most
        one phase; start a move for any shard whose desired home has
        held for `hysteresis_steps` consecutive rounds."""
        with self._step_lock:
            with self._lock:
                desired = dict(self._desired)
                excluded = self._excluded
                active_shards = set(self._active)
            for shard in range(self.plane.map.shards):
                if shard in active_shards:
                    self._advance(shard, excluded)
                    continue
                home = desired.get(shard)
                if home is None:
                    continue
                if not self._walk_needed(shard, home, excluded):
                    with self._lock:
                        self._streak[shard] = 0
                    continue
                with self._lock:
                    self._streak[shard] = self._streak.get(shard, 0) + 1
                    confirmed = self._streak[shard] >= self.hysteresis_steps
                if not confirmed:
                    continue
                victim = self._pick_victim(shard, home, excluded)
                if victim is None:
                    continue
                move = {
                    "shard": shard,
                    "phase": PHASE_ADD,
                    "victim": victim,
                    "targetRegion": self._pick_target_region(
                        shard, home, excluded
                    ),
                    "desiredHome": home,
                    "learner": None,
                    "term": None,
                    "syncSteps": 0,
                }
                with self._lock:
                    self._active[shard] = move
                self._advance(shard, excluded)

    def _advance(self, shard: int, excluded: frozenset) -> None:
        from ..chaos.injector import consult
        from ..core import metrics

        with self._lock:
            move = self._active.get(shard)
        if move is None:
            return
        group = self.plane.shard_groups[shard]
        leader = group.leader()
        if leader is None:
            # Leaderless: neither progress nor fencing — the term fence
            # only fires against an OBSERVED new leader, and every
            # transition below needs a committing leader anyway.
            return
        term = leader.elector.term
        if move["term"] is not None and term != move["term"]:
            # A different epoch took over mid-walk: the move's quorum
            # reasoning belonged to the fenced term. Unwind.
            self._abort(move, f"term fence: {move['term']} -> {term}")
            return
        fault = consult(
            "shard.migrate",
            f"shard={shard} phase={move['phase']}",
            injector=self.injector,
        )
        if fault is not None:
            if fault.kind == "stall":
                return  # the walk holds this step
            if fault.kind == "break" and move["phase"] == PHASE_SYNC:
                move = dict(move, syncSteps=move["syncSteps"] + 1)
                if move["syncSteps"] >= self.max_sync_steps:
                    self._abort(move, "learner stream broken past budget")
                    return
                with self._lock:
                    self._active[shard] = move
                return  # this sync attempt failed; retry next step
            self._abort(move, f"chaos {fault.kind}")
            return
        phase = move["phase"]
        try:
            if phase == PHASE_ADD:
                coord = leader.coordinator
                if coord is None or (
                    coord.store is not None
                    and coord.store.last_record is not None
                    and not coord.replicate()
                ):
                    # The leader cannot currently commit its own head —
                    # a dark MINORITY leader whose voters are all behind
                    # the cut. Minting a learner now would burn it on a
                    # doomed promote record, so the move holds until a
                    # committing leader exists (the dark one steps down
                    # on quorum loss and a reachable voter takes over).
                    # A dark MAJORITY leader passes this probe through
                    # its same-region peers and proceeds to walk itself
                    # out — the availability clause stays intact.
                    return
                learner = group.add_learner()
                region = move["targetRegion"]
                self.plane.topology.place(learner.replica_id, region)
                self.plane.replica_region[learner.replica_id] = region
                move = dict(move, learner=learner.replica_id,
                            term=term, phase=PHASE_SYNC)
                metrics.shard_migrations_total.inc(PHASE_ADD, "ok")
            elif phase == PHASE_SYNC:
                lag = group.sync_learner(move["learner"])
                move = dict(move, syncSteps=move["syncSteps"] + 1)
                if lag == 0:
                    move = dict(move, phase=PHASE_PROMOTE)
                    metrics.shard_migrations_total.inc(PHASE_SYNC, "ok")
                elif move["syncSteps"] >= self.max_sync_steps:
                    self._abort(move, f"sync stuck at lag {lag}")
                    return
            elif phase == PHASE_PROMOTE:
                if not group.promote_learner(move["learner"]):
                    self._abort(
                        move, "membership record missed quorum at promote"
                    )
                    return
                move = dict(move, phase=PHASE_RETIRE)
                metrics.shard_migrations_total.inc(PHASE_PROMOTE, "ok")
            elif phase == PHASE_RETIRE:
                ok = group.retire_replica(move["victim"])
                self.plane.topology.unplace(move["victim"])
                self.plane.replica_region.pop(move["victim"], None)
                metrics.shard_migrations_total.inc(
                    PHASE_RETIRE, "ok" if ok else "noquorum"
                )
                self._complete(move)
                return
        except Exception as exc:
            self._abort(move, f"{phase} failed: {exc}")
            return
        with self._lock:
            self._active[shard] = move

    def _complete(self, move: dict) -> None:
        from ..core import metrics

        shard = move["shard"]
        done = dict(move, phase="done", outcome="completed")
        with self._lock:
            self._active.pop(shard, None)
            self._streak[shard] = 0
            self._history = (self._history + [done])[-HISTORY_LIMIT:]
            desired = self._desired.get(shard)
            excluded = self._excluded
        metrics.shard_migrations_total.inc("complete", "ok")
        if desired is not None and not self._walk_needed(
            shard, desired, excluded
        ):
            # The WALK (possibly several moves) is done: the planned
            # home is now the actual home — adopt it so /debug/shards,
            # quorum_homed_in and the next solve's stickiness all see
            # the migrated placement.
            self.plane.homes[shard] = desired
            self.plane.map.homes[shard] = desired

    def _abort(self, move: dict, reason: str) -> None:
        """Unwind to the pre-move membership: detach the learner — or,
        past promote, retire the just-promoted voter (the inverse
        single-change) — and release the shard's move slot. The victim
        replica was never touched before retire, so pre-move membership
        is restored exactly."""
        from ..core import metrics

        shard = move["shard"]
        learner = move.get("learner")
        if learner is not None:
            try:
                self.plane.shard_groups[shard].retire_replica(learner)
            except Exception:
                import logging

                logging.getLogger("jobset_tpu.shard").exception(
                    "abort-unwind of shard %s move (learner %s) failed",
                    shard, learner,
                )
            self.plane.topology.unplace(learner)
            self.plane.replica_region.pop(learner, None)
        metrics.shard_migrations_total.inc(move["phase"], "abort")
        done = dict(move, outcome="aborted", reason=reason)
        with self._lock:
            self._active.pop(shard, None)
            self._streak[shard] = 0
            self._history = (self._history + [done])[-HISTORY_LIMIT:]


__all__ = [
    "HISTORY_LIMIT",
    "MigrationController",
    "PHASE_ADD",
    "PHASE_PROMOTE",
    "PHASE_RETIRE",
    "PHASE_SYNC",
]
