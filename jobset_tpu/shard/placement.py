"""Shard-home assignment as a solver problem (NL-CPS style).

Each shard's quorum needs a home region (where its leader and replica
majority live — docs/sharding.md "Quorum-per-shard topology"). The
assignment minimizes, per shard:

* **front-door latency**: every write's quorum round trip starts at the
  router, so a home far from the front-door region taxes every request;
* **failure-domain concentration**: each additional shard homed in the
  same region raises the blast radius of one region isolation, so later
  slots of a region cost progressively more.

The cost surface is a ``shards x (regions * slots)`` matrix solved
through the existing :class:`placement.solver.AssignmentSolver` — the
same auction machinery that places gangs on domains — with a
deterministic greedy argmin fallback over the identical matrix when the
solver stack is unavailable (decisions coincide on these tiny, strictly
slot-monotone surfaces; the parity test pins it). ``resolve`` is called
again on every region cut/heal with the faulted regions priced at
+infinity, which is what "re-solved on topology change" means: the
planned homes move off the dark region and come back when it heals.
"""

from __future__ import annotations

from typing import Iterable, Optional

import numpy as np

from .topology import RegionTopology

# Concentration penalty per extra shard homed in one region, in the same
# ms units as the latency column. Dominated by typical inter-region
# latency spreads only after several shards stack up, so the solver
# prefers nearby regions until concentration starts to bite — the
# latency/failure-domain tradeoff the cost model exists to encode.
CONCENTRATION_PENALTY_MS = 25.0


def placement_cost(topology: RegionTopology, shards: int,
                   excluded: Iterable[str] = ()) -> tuple[np.ndarray, list]:
    """(cost matrix, slot->region list): one column per (region, slot)
    with ceil(shards/regions) slots per region, latency from the
    front-door region plus a per-slot concentration ramp; excluded
    (faulted) regions cost +inf."""
    regions = list(topology.regions)
    slots_per_region = -(-shards // len(regions))  # ceil
    slot_regions = [
        region for region in regions for _ in range(slots_per_region)
    ]
    dark = set(excluded)
    cost = np.empty((shards, len(slot_regions)), dtype=np.float64)
    for column, region in enumerate(slot_regions):
        slot = column % slots_per_region
        if region in dark:
            base = np.inf
        else:
            base = (
                topology.latency_ms(topology.front_door_region, region)
                + slot * CONCENTRATION_PENALTY_MS
            )
        cost[:, column] = base
    return cost, slot_regions


def _greedy_assign(cost: np.ndarray) -> list[int]:
    """Deterministic argmin assignment over the shared cost matrix: each
    shard (row order) takes the cheapest free column. On these surfaces
    every row shares one column ordering, so greedy IS optimal — and it
    doubles as the solver-stack-unavailable fallback."""
    taken: set[int] = set()
    out: list[int] = []
    for row in range(cost.shape[0]):
        best = min(
            (c for c in range(cost.shape[1]) if c not in taken),
            key=lambda c: (cost[row, c], c),
        )
        taken.add(best)
        out.append(best)
    return out


def solve_shard_homes(topology: RegionTopology, shards: int,
                      excluded: Iterable[str] = (),
                      solver: Optional[object] = None,
                      current: Optional[dict[int, str]] = None,
                      stickiness_ms: float = 0.0) -> dict[int, str]:
    """shard -> home region via the assignment solver (greedy fallback).

    With every region excluded (total blackout) the exclusion is ignored:
    a placement must always exist — the plan is advisory while the fault
    persists.

    `current`/`stickiness_ms` is the anti-thrash hysteresis knob
    (docs/sharding.md "Replica migration"): each shard's CURRENT home
    columns are discounted by `stickiness_ms`, so a marginally-cheaper
    alternative (a latency spread smaller than the stickiness) never
    uproots a settled quorum — only a real event (the home going dark
    prices it at +inf, which no discount rescues) moves the plan. The
    default 0.0 keeps the plain solve byte-identical with prior builds."""
    cost, slot_regions = placement_cost(topology, shards, excluded)
    if not np.isfinite(cost).any():
        cost, slot_regions = placement_cost(topology, shards, ())
    if current and stickiness_ms > 0.0:
        for shard, home in current.items():
            if not 0 <= int(shard) < cost.shape[0]:
                continue
            for column, region in enumerate(slot_regions):
                if region == home and np.isfinite(cost[int(shard), column]):
                    cost[int(shard), column] -= float(stickiness_ms)
    # The auction benefit surface cannot hold inf: cap dark columns at a
    # big-M strictly above any finite column so they are only ever chosen
    # when nothing else exists.
    finite = cost[np.isfinite(cost)]
    big_m = (finite.max() if finite.size else 0.0) + 1e6
    solvable = np.where(np.isfinite(cost), cost, big_m)
    assignment = None
    try:
        if solver is None:
            from ..placement.solver import AssignmentSolver

            solver = AssignmentSolver()
        assignment = solver.solve(solvable)
    except Exception:
        assignment = None
    if assignment is None or any(
        int(a) < 0 or int(a) >= len(slot_regions) for a in assignment
    ):
        assignment = _greedy_assign(solvable)
    return {
        shard: slot_regions[int(column)]
        for shard, column in enumerate(assignment)
    }


__all__ = [
    "CONCENTRATION_PENALTY_MS",
    "placement_cost",
    "solve_shard_homes",
]
