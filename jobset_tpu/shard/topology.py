"""Simulated region topology for shard placement and region faults.

Regions are the failure domains of the sharded control plane: each shard
group's replicas live in regions, and ``chaos/net.py`` region isolations
cut every directed link crossing a region boundary. Pairwise latencies
are seeded and symmetric (a pure function of ``(seed, region pair)``) so
the placement solve — and therefore the shard map — is byte-identical
across runs; the front door sits in a designated region (default: the
first), which is where the latency column of the placement cost comes
from.
"""

from __future__ import annotations

import hashlib
from typing import Iterable, Optional

# The front door's identity on the network fault model's directed links
# (chaos/net.py): every router dispatch is one delivery over
# (FRONT_DOOR_SRC, shard-leader replica id).
FRONT_DOOR_SRC = "front-door"


def _pair_latency_ms(seed: int, a: str, b: str) -> float:
    """Deterministic inter-region latency in ms: 10..59 ms drawn from a
    keyed digest of the (sorted) pair — stable, symmetric, never the
    process RNG."""
    lo, hi = sorted((a, b))
    digest = hashlib.blake2b(
        f"{lo}|{hi}".encode(), digest_size=4,
        key=f"region-latency-{seed}".encode(),
    ).digest()
    return 10.0 + int.from_bytes(digest, "big") % 50


class RegionTopology:
    """Named regions + seeded pairwise latencies + the actor->region map.

    ``place(actor, region)`` registers a control-plane actor (a shard
    replica id, the front door) in a region; ``isolation_links(region)``
    yields every directed cross-boundary link a region isolation must
    cut — the single definition the scenarios AND ``bench --ha --shards``
    both drive, so they measure the same fault.
    """

    def __init__(self, regions: Iterable[str] = ("region-a", "region-b",
                                                 "region-c"),
                 seed: int = 0, front_door_region: Optional[str] = None):
        self.regions = list(regions)
        if not self.regions:
            raise ValueError("a topology needs at least one region")
        self.seed = int(seed)
        self.front_door_region = front_door_region or self.regions[0]
        # actor id -> region; the front door registers itself too, so a
        # front-door-region isolation is expressible.
        self.actor_region: dict[str, str] = {
            FRONT_DOOR_SRC: self.front_door_region
        }

    def place(self, actor: str, region: str) -> None:
        if region not in self.regions:
            raise ValueError(
                f"unknown region {region!r} (regions: {self.regions})"
            )
        self.actor_region[actor] = region

    def unplace(self, actor: str) -> None:
        """Forget a retired actor: subsequent region isolations no longer
        schedule cuts for its links (a migrated-away replica's id must
        not keep inflating the deterministic cut schedule)."""
        self.actor_region.pop(actor, None)

    def latency_ms(self, a: str, b: str) -> float:
        """Symmetric inter-region latency (0 within a region)."""
        if a == b:
            return 0.0
        return _pair_latency_ms(self.seed, a, b)

    def actors_in(self, region: str) -> list[str]:
        return sorted(
            actor for actor, r in self.actor_region.items() if r == region
        )

    def isolation_links(self, region: str) -> list[tuple[str, str]]:
        """Every directed link a full isolation of `region` severs: both
        directions between each actor inside and each actor outside,
        sorted for deterministic cut scheduling."""
        inside = set(self.actors_in(region))
        outside = [
            actor for actor in sorted(self.actor_region)
            if actor not in inside
        ]
        links: list[tuple[str, str]] = []
        for a in sorted(inside):
            for b in outside:
                links.append((a, b))
                links.append((b, a))
        return links

    def to_dict(self) -> dict:
        return {
            "regions": list(self.regions),
            "seed": self.seed,
            "frontDoorRegion": self.front_door_region,
            "latencyMs": {
                f"{a}|{b}": self.latency_ms(a, b)
                for i, a in enumerate(self.regions)
                for b in self.regions[i + 1:]
            },
            "actors": dict(sorted(self.actor_region.items())),
        }


__all__ = ["FRONT_DOOR_SRC", "RegionTopology"]
