"""Well-known label/annotation keys, condition types and event reasons.

Mirrors the reference API's key space (reference:
`api/jobset/v1alpha2/jobset_types.go:22-74` and
`pkg/constants/constants.go:19-93`) so that workloads written against
JobSet's labels/annotations find the same contract here.
"""

# ---------------------------------------------------------------------------
# Label / annotation keys (jobset_types.go:22-58)
# ---------------------------------------------------------------------------

JOBSET_NAME_KEY = "jobset.sigs.k8s.io/jobset-name"
REPLICATED_JOB_REPLICAS_KEY = "jobset.sigs.k8s.io/replicatedjob-replicas"
REPLICATED_JOB_NAME_KEY = "jobset.sigs.k8s.io/replicatedjob-name"
# Index of the Job replica within its parent ReplicatedJob (0..replicas-1).
JOB_INDEX_KEY = "jobset.sigs.k8s.io/job-index"
# Index of the Job within the entire JobSet (0..total_jobs-1).
JOB_GLOBAL_INDEX_KEY = "jobset.sigs.k8s.io/job-global-index"
# SHA256 hash of the namespaced job name; unique id for the job.
JOB_KEY = "jobset.sigs.k8s.io/job-key"
# Restart attempt this job belongs to (constants.go:29).
RESTARTS_KEY = "jobset.sigs.k8s.io/restart-attempt"
# Exclusive-placement topology annotation; value is the node topology label
# key defining the domain (e.g. a rack or TPU-slice label).
EXCLUSIVE_KEY = "alpha.jobset.sigs.k8s.io/exclusive-topology"
# Flag annotation: use the node-selector strategy for exclusive placement
# (nodes pre-labelled out of band) instead of affinity injection.
NODE_SELECTOR_STRATEGY_KEY = "alpha.jobset.sigs.k8s.io/node-selector"
NAMESPACED_JOB_KEY = "alpha.jobset.sigs.k8s.io/namespaced-job"
NO_SCHEDULE_TAINT_KEY = "alpha.jobset.sigs.k8s.io/no-schedule"
# Stable endpoint of the coordinator pod, stamped on jobs + pods.
COORDINATOR_KEY = "jobset.sigs.k8s.io/coordinator"

# Annotation stamped by a PlacementProvider when it has pinned a job's
# topology domain via a precomputed nodeSelector plan (new in this build; the
# pod webhooks skip planned pods the way they skip the nodeSelector strategy).
PLACEMENT_PLAN_KEY = "tpu.jobset.x-k8s.io/placement-plan"

# Admission-queue label stamped onto queue-managed JobSets (Kueue's
# `kueue.x-k8s.io/queue-name` analog; the spec field is authoritative, the
# label exists so selectors/informers can filter queued workloads).
QUEUE_NAME_KEY = "tpu.jobset.x-k8s.io/queue-name"

# Reserved managedBy value for the built-in controller.
JOBSET_CONTROLLER_NAME = "jobset.sigs.k8s.io/jobset-controller"

# Pod completion-index annotation (the simulated Job controller stamps this
# the way the k8s Job controller stamps batch.kubernetes.io/job-completion-index).
POD_COMPLETION_INDEX_KEY = "batch.kubernetes.io/job-completion-index"

# ---------------------------------------------------------------------------
# JobSet condition types (jobset_types.go:60-74)
# ---------------------------------------------------------------------------

JOBSET_COMPLETED = "Completed"
JOBSET_FAILED = "Failed"
JOBSET_SUSPENDED = "Suspended"
JOBSET_STARTUP_POLICY_IN_PROGRESS = "StartupPolicyInProgress"
JOBSET_STARTUP_POLICY_COMPLETED = "StartupPolicyCompleted"

# ---------------------------------------------------------------------------
# Enumerations
# ---------------------------------------------------------------------------

OPERATOR_ALL = "All"
OPERATOR_ANY = "Any"

FAIL_JOBSET = "FailJobSet"
RESTART_JOBSET = "RestartJobSet"
RESTART_JOBSET_AND_IGNORE_MAX_RESTARTS = "RestartJobSetAndIgnoreMaxRestarts"
FAILURE_POLICY_ACTIONS = (
    FAIL_JOBSET,
    RESTART_JOBSET,
    RESTART_JOBSET_AND_IGNORE_MAX_RESTARTS,
)

STARTUP_ANY_ORDER = "AnyOrder"
STARTUP_IN_ORDER = "InOrder"

COMPLETION_MODE_INDEXED = "Indexed"
COMPLETION_MODE_NON_INDEXED = "NonIndexed"

RESTART_POLICY_ON_FAILURE = "OnFailure"
RESTART_POLICY_NEVER = "Never"
RESTART_POLICY_ALWAYS = "Always"

# Job terminal condition types (batchv1 analog).
JOB_COMPLETE = "Complete"
JOB_FAILED = "Failed"

# Supported job failure reasons for failure-policy rules
# (jobset_webhook.go:68-74; mirrors batchv1 job failure reasons).
JOB_REASON_BACKOFF_LIMIT_EXCEEDED = "BackoffLimitExceeded"
JOB_REASON_DEADLINE_EXCEEDED = "DeadlineExceeded"
JOB_REASON_FAILED_INDEXES = "FailedIndexes"
JOB_REASON_MAX_FAILED_INDEXES_EXCEEDED = "MaxFailedIndexesExceeded"
JOB_REASON_POD_FAILURE_POLICY = "PodFailurePolicy"
VALID_ON_JOB_FAILURE_REASONS = (
    JOB_REASON_BACKOFF_LIMIT_EXCEEDED,
    JOB_REASON_DEADLINE_EXCEEDED,
    JOB_REASON_FAILED_INDEXES,
    JOB_REASON_MAX_FAILED_INDEXES_EXCEEDED,
    JOB_REASON_POD_FAILURE_POLICY,
)

# ---------------------------------------------------------------------------
# Operating parameters + event reasons (constants.go:19-93)
# ---------------------------------------------------------------------------

MAX_PARALLELISM = 50

REACHED_MAX_RESTARTS_REASON = "ReachedMaxRestarts"
REACHED_MAX_RESTARTS_MESSAGE = "jobset failed due to reaching max number of restarts"

FAILED_JOBS_REASON = "FailedJobs"
FAILED_JOBS_MESSAGE = "jobset failed due to one or more job failures"

ALL_JOBS_COMPLETED_REASON = "AllJobsCompleted"
ALL_JOBS_COMPLETED_MESSAGE = "jobset completed successfully"

JOB_CREATION_FAILED_REASON = "JobCreationFailed"
HEADLESS_SERVICE_CREATION_FAILED_REASON = "HeadlessServiceCreationFailed"

EXCLUSIVE_PLACEMENT_VIOLATION_REASON = "ExclusivePlacementViolation"
EXCLUSIVE_PLACEMENT_VIOLATION_MESSAGE = (
    "Pod violated JobSet exclusive placement policy"
)

IN_ORDER_STARTUP_POLICY_IN_PROGRESS_REASON = "InOrderStartupPolicyInProgress"
IN_ORDER_STARTUP_POLICY_IN_PROGRESS_MESSAGE = "in order startup policy is in progress"
IN_ORDER_STARTUP_POLICY_COMPLETED_REASON = "InOrderStartupPolicyCompleted"
IN_ORDER_STARTUP_POLICY_COMPLETED_MESSAGE = "in order startup policy has completed"

JOBSET_RESTART_REASON = "Restarting"

JOBSET_SUSPENDED_REASON = "SuspendedJobs"
JOBSET_SUSPENDED_MESSAGE = "jobset is suspended"
JOBSET_RESUMED_REASON = "ResumeJobs"
JOBSET_RESUMED_MESSAGE = "jobset is resumed"

# Admission-queue event reasons (queue/ subsystem; Kueue workload events
# analog: Pending/Admitted/Preempted/Requeued).
QUEUE_PENDING_REASON = "QueuePending"
QUEUE_ADMITTED_REASON = "QueueAdmitted"
QUEUE_PREEMPTED_REASON = "QueuePreempted"
QUEUE_REQUEUED_REASON = "QueueRequeued"
QUEUE_RELEASED_REASON = "QueueReleased"

FAIL_JOBSET_ACTION_REASON = "FailJobSetFailurePolicyAction"
FAIL_JOBSET_ACTION_MESSAGE = "applying FailJobSet failure policy action"

RESTART_JOBSET_ACTION_REASON = "RestartJobSetFailurePolicyAction"
RESTART_JOBSET_ACTION_MESSAGE = "applying RestartJobSet failure policy action"

RESTART_JOBSET_AND_IGNORE_MAX_RESTARTS_ACTION_REASON = (
    "RestartJobSetAndIgnoreMaxRestartsFailurePolicyAction"
)
RESTART_JOBSET_AND_IGNORE_MAX_RESTARTS_ACTION_MESSAGE = (
    "applying RestartJobSetAndIgnoreMaxRestarts failure policy action"
)

# Event types (corev1 analog).
EVENT_NORMAL = "Normal"
EVENT_WARNING = "Warning"

# Pod condition used to mark controller-initiated deletions so that pod
# failure policies can ignore them (pod_controller.go:208-215).
POD_CONDITION_DISRUPTION_TARGET = "DisruptionTarget"
