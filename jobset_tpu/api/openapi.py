"""OpenAPI (swagger v2) schema for the JobSet wire format.

The reference publishes a generated OpenAPI spec for its CRD types
(`hack/swagger/main.go` emitting the `zz_generated.openapi.go`
definitions; `sdk/python/` is generated from that artifact). This module
is the analog: a machine-readable schema of the exact manifest shape
`api.serialization` accepts and emits, so third-party client generators
(openapi-generator, swagger-codegen) can build typed SDKs against the
controller without reading Python.

The schema is hand-declared against the same camelCase wire keys the
serializer owns — and fidelity is TESTED, not assumed: the suite builds a
maximal manifest from this schema and strict-loads it through the
serializer (schema ⊆ serializer), and serializes a maximal JobSet and
validates it against this schema (serializer ⊆ schema), so drift in
either direction fails (tests/test_openapi.py).

Served at ``GET /openapi/v2`` by the controller server; dumped by
``jobset-tpu openapi`` for offline generator use.
"""

from __future__ import annotations

import datetime as _datetime
import functools
from typing import Any

from .serialization import API_VERSION, WORKLOAD_KEY

GROUP = "jobset.x-k8s.io"
VERSION = API_VERSION.rsplit("/", 1)[1]
_PREFIX = f"io.x-k8s.jobset.{VERSION}"


def _ref(name: str) -> dict:
    return {"$ref": f"#/definitions/{_PREFIX}.{name}"}


def _obj(description: str, properties: dict, required: list[str] | None = None) -> dict:
    out: dict[str, Any] = {
        "type": "object",
        "description": description,
        "properties": properties,
    }
    if required:
        out["required"] = required
    return out


_STR = {"type": "string"}
_INT = {"type": "integer", "format": "int32"}
_BOOL = {"type": "boolean"}
_STR_MAP = {"type": "object", "additionalProperties": {"type": "string"}}
_STR_LIST = {"type": "array", "items": {"type": "string"}}
# Opaque k8s payloads the control plane round-trips without inspecting.
_OPAQUE_LIST = {"type": "array", "items": {"type": "object"}}


@functools.lru_cache(maxsize=1)
def _definitions() -> dict:
    """The definitions map, keyed like the reference's generated spec.
    Cached: it is immutable and consulted on every create/update/admission
    request (callers must not mutate the returned tree)."""
    return {
        f"{_PREFIX}.JobSet": _obj(
            "JobSet groups replicated Jobs under shared lifecycle, network "
            "identity, and placement policy (jobset_types.go:347-357 analog).",
            {
                "apiVersion": _STR,
                "kind": _STR,
                "metadata": _ref("ObjectMeta"),
                "spec": _ref("JobSetSpec"),
                "status": _ref("JobSetStatus"),
            },
        ),
        f"{_PREFIX}.ObjectMeta": _obj(
            "Subset of k8s ObjectMeta the framework consumes.",
            {
                "name": _STR,
                "generateName": _STR,
                "namespace": _STR,
                "uid": _STR,
                "labels": _STR_MAP,
                "annotations": _STR_MAP,
                "creationTimestamp": _STR,
            },
        ),
        f"{_PREFIX}.JobSetSpec": _obj(
            "Desired state (jobset_types.go:76-160 analog).",
            {
                "replicatedJobs": {
                    "type": "array", "items": _ref("ReplicatedJob"),
                },
                "network": _ref("Network"),
                "successPolicy": _ref("SuccessPolicy"),
                "failurePolicy": _ref("FailurePolicy"),
                "startupPolicy": _ref("StartupPolicy"),
                "suspend": _BOOL,
                "coordinator": _ref("Coordinator"),
                "managedBy": _STR,
                "ttlSecondsAfterFinished": _INT,
                "queueName": _STR,
                "priority": _INT,
            },
        ),
        f"{_PREFIX}.ReplicatedJob": _obj(
            "Stamps `replicas` Jobs from one template.",
            {
                "name": _STR,
                "replicas": _INT,
                "template": _ref("JobTemplateSpec"),
            },
            required=["name"],
        ),
        f"{_PREFIX}.JobTemplateSpec": _obj(
            "batchv1 JobTemplateSpec analog surface.",
            {
                "metadata": _ref("TemplateMeta"),
                "spec": _ref("JobSpec"),
            },
        ),
        f"{_PREFIX}.TemplateMeta": _obj(
            "Labels/annotations stamped onto created children.",
            {"labels": _STR_MAP, "annotations": _STR_MAP},
        ),
        f"{_PREFIX}.JobSpec": _obj(
            "batchv1 JobSpec analog surface.",
            {
                "parallelism": _INT,
                "completions": _INT,
                "completionMode": _STR,
                "backoffLimit": _INT,
                "suspend": _BOOL,
                "activeDeadlineSeconds": _INT,
                "template": _ref("PodTemplateSpec"),
            },
        ),
        f"{_PREFIX}.PodTemplateSpec": _obj(
            "corev1 PodTemplateSpec analog surface.",
            {
                "metadata": _ref("TemplateMeta"),
                "spec": _ref("PodSpec"),
            },
        ),
        f"{_PREFIX}.PodSpec": _obj(
            "corev1 PodSpec analog surface; container/volume lists are "
            "round-tripped opaquely, and the vendor workload key carries "
            "the JAX runtime launch config.",
            {
                "restartPolicy": _STR,
                "nodeSelector": _STR_MAP,
                "tolerations": {"type": "array", "items": _ref("Toleration")},
                "affinity": _ref("Affinity"),
                "subdomain": _STR,
                "hostname": _STR,
                "schedulingGates": {
                    "type": "array",
                    # Untyped items: the serializer accepts both the k8s
                    # object form ({"name": ...}) and a bare gate-name
                    # string (swagger v2 has no oneOf to express that).
                    "items": {
                        "description": "gate object ({'name': ...}) or name string",
                    },
                },
                "nodeName": _STR,
                "containers": _OPAQUE_LIST,
                "initContainers": _OPAQUE_LIST,
                "volumes": _OPAQUE_LIST,
                WORKLOAD_KEY: {"type": "object"},
            },
        ),
        f"{_PREFIX}.Toleration": _obj(
            "corev1 Toleration analog surface.",
            {
                "key": _STR,
                "operator": {"type": "string", "enum": ["Equal", "Exists"]},
                "value": _STR,
                "effect": _STR,
            },
        ),
        f"{_PREFIX}.Affinity": _obj(
            "Reduced job-key affinity form the placement webhooks inject.",
            {
                "podAffinity": {"type": "array", "items": _ref("AffinityTerm")},
                "podAntiAffinity": {
                    "type": "array", "items": _ref("AffinityTerm"),
                },
            },
        ),
        f"{_PREFIX}.AffinityTerm": _obj(
            "One topology-scoped job-key term.",
            {
                "topologyKey": _STR,
                "jobKeyIn": _STR_LIST,
                "jobKeyExists": _BOOL,
                "jobKeyNotIn": _STR_LIST,
            },
        ),
        f"{_PREFIX}.Network": _obj(
            "DNS rendezvous config (jobset_types.go Network analog).",
            {
                "enableDNSHostnames": _BOOL,
                "subdomain": _STR,
                "publishNotReadyAddresses": _BOOL,
            },
        ),
        f"{_PREFIX}.SuccessPolicy": _obj(
            "When the JobSet is Completed.",
            {
                "operator": {"type": "string", "enum": ["All", "Any"]},
                "targetReplicatedJobs": _STR_LIST,
            },
        ),
        f"{_PREFIX}.FailurePolicy": _obj(
            "Restart budget + ordered rules.",
            {
                "maxRestarts": _INT,
                "rules": {"type": "array", "items": _ref("FailurePolicyRule")},
            },
        ),
        f"{_PREFIX}.FailurePolicyRule": _obj(
            "First matching rule decides the action.",
            {
                "name": _STR,
                "action": {
                    "type": "string",
                    "enum": [
                        "FailJobSet", "RestartJobSet",
                        "RestartJobSetAndIgnoreMaxRestarts",
                    ],
                },
                "onJobFailureReasons": _STR_LIST,
                "targetReplicatedJobs": _STR_LIST,
            },
        ),
        f"{_PREFIX}.StartupPolicy": _obj(
            "Startup ordering of replicated jobs.",
            {
                "startupPolicyOrder": {
                    "type": "string", "enum": ["AnyOrder", "InOrder"],
                },
            },
        ),
        f"{_PREFIX}.Coordinator": _obj(
            "Stable coordinator pod identity published on the annotation.",
            {"replicatedJob": _STR, "jobIndex": _INT, "podIndex": _INT},
        ),
        f"{_PREFIX}.JobSetStatus": _obj(
            "Observed state (single-status-write discipline).",
            {
                "restarts": _INT,
                "restartsCountTowardsMax": _INT,
                "terminalState": _STR,
                "conditions": {"type": "array", "items": _ref("Condition")},
                "replicatedJobsStatus": {
                    "type": "array", "items": _ref("ReplicatedJobStatus"),
                },
            },
        ),
        f"{_PREFIX}.Condition": _obj(
            "metav1.Condition analog surface.",
            {
                "type": _STR,
                "status": _STR,
                "reason": _STR,
                "message": _STR,
                "lastTransitionTime": _STR,
            },
        ),
        f"{_PREFIX}.ReplicatedJobStatus": _obj(
            "Per-replicated-job child rollup.",
            {
                "name": _STR,
                "ready": _INT,
                "succeeded": _INT,
                "failed": _INT,
                "active": _INT,
                "suspended": _INT,
            },
        ),
    }


def openapi_spec() -> dict:
    """The swagger v2 document (the reference artifact's shape: a
    definitions map under a minimal swagger header)."""
    return {
        "swagger": "2.0",
        "info": {
            "title": "JobSet-TPU API",
            "version": VERSION,
            "description": (
                f"Schema of the {API_VERSION} wire format served by the "
                "jobset-tpu controller."
            ),
        },
        "definitions": _definitions(),
    }


def validate_manifest(
    manifest: dict, definition: str = "JobSet", pruning: bool = False
) -> list[str]:
    """Validate `manifest` against a schema definition; returns a list of
    problems (empty = valid). Recursive structural check: types, enums,
    required fields, and UNKNOWN properties (additionalProperties defaults
    closed here, matching the serializer's strict mode).

    pruning=True skips unknown-property reporting — apiserver structural-
    schema semantics, where unknown fields are pruned rather than
    rejected. This mode is the create/update path's CRD-schema gate: the
    reference's enum and type constraints live in kubebuilder CRD
    markers (jobset_types.go `+kubebuilder:validation:Enum=All;Any` etc.)
    that the apiserver enforces BEFORE webhooks run; here the schema is
    that layer."""
    defs = _definitions()
    problems: list[str] = []

    def walk(value, schema: dict, path: str) -> None:
        if "$ref" in schema:
            walk(value, defs[schema["$ref"].rsplit("/", 1)[1]], path)
            return
        stype = schema.get("type")
        if stype == "object":
            if not isinstance(value, dict):
                problems.append(f"{path}: expected object, got {type(value).__name__}")
                return
            props = schema.get("properties")
            extra = schema.get("additionalProperties")
            if props is not None:
                for key, sub in value.items():
                    if key in props:
                        walk(sub, props[key], f"{path}.{key}")
                    elif extra is None:
                        if not pruning:
                            problems.append(f"{path}: unknown property {key!r}")
                    elif isinstance(extra, dict):
                        walk(sub, extra, f"{path}.{key}")
            elif isinstance(extra, dict):
                for key, sub in value.items():
                    walk(sub, extra, f"{path}.{key}")
            for req in schema.get("required", []):
                if req not in value:
                    problems.append(f"{path}: missing required {req!r}")
        elif stype == "array":
            if not isinstance(value, list):
                problems.append(f"{path}: expected array, got {type(value).__name__}")
                return
            for i, item in enumerate(value):
                walk(item, schema["items"], f"{path}[{i}]")
        elif stype == "string":
            # An explicit YAML null means "unset" on the wire (apiserver
            # semantics; the serializer treats it the same) — no type or
            # enum complaint. yaml.safe_load also turns unquoted
            # timestamps into datetime objects; those serialize back to
            # strings, so they satisfy string fields.
            if value is None:
                pass
            elif not isinstance(value, (str, _datetime.date)):
                problems.append(f"{path}: expected string, got {type(value).__name__}")
            elif "enum" in schema and value not in schema["enum"]:
                problems.append(f"{path}: {value!r} not in {schema['enum']}")
        elif stype == "integer":
            # Mirror the serializer's _as_int coercion: numeric strings
            # and integral floats (common from templating) are accepted.
            if isinstance(value, bool):
                problems.append(f"{path}: expected integer, got bool")
            elif value is None or isinstance(value, int):
                pass
            else:
                try:
                    int(value)
                except (TypeError, ValueError):
                    problems.append(
                        f"{path}: expected integer, got {type(value).__name__}"
                    )
        elif stype == "boolean":
            if value is not None and not isinstance(value, bool):
                problems.append(f"{path}: expected boolean, got {type(value).__name__}")

    walk(manifest, defs[f"{_PREFIX}.{definition}"], definition)
    return problems
