"""JobSet wire format: dict/YAML <-> dataclass conversion.

The wire schema follows the reference CRD's camelCase field names
(`api/jobset/v1alpha2/jobset_types.go:76-357`), so a manifest written for the
reference (`apiVersion: jobset.x-k8s.io/v1alpha2, kind: JobSet`) loads
directly into this framework's `JobSet` dataclasses, and `to_dict`/`to_yaml`
emit manifests a reference user would recognise.  Unknown fields are ignored
by default (k8s-style pruning); `strict=True` raises on them instead.

Pod specs carry an opaque `workload` payload on our side; on the wire that is
round-tripped through the standard `containers` list plus a vendor
`x-jobset-tpu/workload` annotation-free extension key, so k8s-shaped pod
templates survive a load/dump cycle.
"""

from __future__ import annotations

import copy
from typing import Any, Optional

import yaml

from . import types as t

API_VERSION = "jobset.x-k8s.io/v1alpha2"
KIND = "JobSet"

# Wire key for the opaque workload payload (not part of the reference CRD;
# carries the JAX runtime launch config the way the reference carries
# container commands).
WORKLOAD_KEY = "x-jobset-tpu/workload"


class SerializationError(ValueError):
    pass


def _check_unknown(d: dict, known: set, where: str, strict: bool) -> None:
    if not strict:
        return
    unknown = set(d) - known
    if unknown:
        raise SerializationError(f"unknown field(s) {sorted(unknown)} in {where}")


def _as_dict(v, where: str) -> dict:
    if v is None:
        return {}
    if not isinstance(v, dict):
        raise SerializationError(f"{where} must be a mapping, got {type(v).__name__}")
    return v


def _as_list(v, where: str) -> list:
    if v is None:
        return []
    if not isinstance(v, list):
        raise SerializationError(f"{where} must be a list, got {type(v).__name__}")
    return v


def _as_int(d: dict, key: str, default: int, where: str) -> int:
    """Scalar fetch where an explicit YAML null (`key:` / `key: ~`) means
    unset, matching apiserver semantics."""
    v = d.get(key)
    if v is None:
        return default
    try:
        return int(v)
    except (TypeError, ValueError):
        raise SerializationError(f"{where}.{key} must be an integer, got {v!r}")


# ---------------------------------------------------------------------------
# from_dict
# ---------------------------------------------------------------------------


def _meta_from(d: Optional[dict], strict: bool) -> t.ObjectMeta:
    d = _as_dict(d, "metadata")
    _check_unknown(
        d,
        {"name", "namespace", "uid", "labels", "annotations",
         "creationTimestamp", "generateName"},
        "metadata",
        strict,
    )
    return t.ObjectMeta(
        name=d.get("name", ""),
        generate_name=d.get("generateName", ""),
        namespace=d.get("namespace", "default"),
        uid=str(d.get("uid", "")),
        labels=dict(d.get("labels") or {}),
        annotations=dict(d.get("annotations") or {}),
    )


def _toleration_from(d: dict) -> t.Toleration:
    return t.Toleration(
        key=d.get("key", ""),
        operator=d.get("operator", "Equal"),
        value=d.get("value", ""),
        effect=d.get("effect", ""),
    )


def _affinity_from(d: Optional[dict]) -> Optional[t.Affinity]:
    """Parse the reduced job-key affinity form this framework injects
    (placement/webhooks.py); arbitrary k8s affinity is out of scope."""
    if not d:
        return None
    d = _as_dict(d, "affinity")

    def terms(key):
        return [
            t.AffinityTerm(
                topology_key=x.get("topologyKey", ""),
                job_key_in=x.get("jobKeyIn"),
                job_key_exists=bool(x.get("jobKeyExists", False)),
                job_key_not_in=x.get("jobKeyNotIn"),
            )
            for x in _as_list(d.get(key), f"affinity.{key}")
        ]

    return t.Affinity(
        pod_affinity=terms("podAffinity"),
        pod_anti_affinity=terms("podAntiAffinity"),
    )


def _affinity_dict(a: Optional[t.Affinity]) -> Optional[dict]:
    if a is None:
        return None

    def terms(lst):
        return [
            _prune({
                "topologyKey": x.topology_key,
                "jobKeyIn": list(x.job_key_in) if x.job_key_in else None,
                "jobKeyExists": x.job_key_exists or None,
                "jobKeyNotIn": list(x.job_key_not_in) if x.job_key_not_in else None,
            })
            for x in lst
        ]

    return _prune({
        "podAffinity": terms(a.pod_affinity),
        "podAntiAffinity": terms(a.pod_anti_affinity),
    }) or None


def _pod_spec_from(d: Optional[dict], strict: bool) -> t.PodSpec:
    d = _as_dict(d, "pod template spec")
    _check_unknown(
        d,
        {"restartPolicy", "nodeSelector", "tolerations", "subdomain", "hostname",
         "schedulingGates", "nodeName", "affinity", "containers",
         "initContainers", "volumes", WORKLOAD_KEY},
        "pod template spec",
        strict,
    )
    gates = []
    for g in _as_list(d.get("schedulingGates"), "schedulingGates"):
        gates.append(g["name"] if isinstance(g, dict) else str(g))
    workload = copy.deepcopy(_as_dict(d.get(WORKLOAD_KEY), WORKLOAD_KEY))
    # Preserve k8s container lists opaquely: the control plane never looks
    # inside them, the runtime layer may (runtime/runner.py). Native k8s
    # fields win over copies embedded in the vendor payload.
    for k in ("containers", "initContainers", "volumes"):
        if k in d:
            if strict and k in workload and workload[k] != d[k]:
                raise SerializationError(
                    f"pod spec has conflicting {k!r} both natively and in {WORKLOAD_KEY}"
                )
            workload[k] = copy.deepcopy(d[k])
    return t.PodSpec(
        restart_policy=d.get("restartPolicy", ""),
        node_selector=dict(d.get("nodeSelector") or {}),
        tolerations=[
            _toleration_from(x) for x in _as_list(d.get("tolerations"), "tolerations")
        ],
        affinity=_affinity_from(d.get("affinity")),
        subdomain=d.get("subdomain", ""),
        hostname=d.get("hostname", ""),
        scheduling_gates=gates,
        node_name=d.get("nodeName", ""),
        workload=workload,
    )


def _pod_template_from(d: Optional[dict], strict: bool) -> t.PodTemplateSpec:
    d = _as_dict(d, "pod template")
    _check_unknown(d, {"metadata", "spec"}, "pod template", strict)
    meta = _as_dict(d.get("metadata"), "pod template metadata")
    annotations = dict(meta.get("annotations") or {})
    spec = _pod_spec_from(d.get("spec"), strict)
    # Strict-CRD manifests (to_k8s_dict) carry the workload payload as a
    # JSON annotation instead of a vendor spec field: absorb it back so
    # the export round-trips losslessly.
    packed = annotations.pop(WORKLOAD_KEY, None)
    opaque = set(spec.workload) - {"containers", "initContainers", "volumes"}
    if packed and not opaque:
        import json as _json

        try:
            restored = _json.loads(packed)
        except ValueError:
            raise SerializationError(
                f"pod template annotation {WORKLOAD_KEY} is not valid JSON"
            )
        if not isinstance(restored, dict):
            raise SerializationError(
                f"pod template annotation {WORKLOAD_KEY} must encode a JSON "
                f"object, got {type(restored).__name__}"
            )
        # Native container fields already absorbed into workload (e.g. the
        # synthesized runner container) win over the annotation's copies.
        native = {
            k: spec.workload[k]
            for k in ("containers", "initContainers", "volumes")
            if k in spec.workload
        }
        spec.workload = {**restored, **native}
    elif packed is not None:
        # Not absorbed (a native workload also present, or an empty
        # string): keep the annotation verbatim rather than dropping it.
        annotations[WORKLOAD_KEY] = packed
    return t.PodTemplateSpec(
        labels=dict(meta.get("labels") or {}),
        annotations=annotations,
        spec=spec,
    )


def _job_spec_from(d: Optional[dict], strict: bool) -> t.JobSpec:
    d = _as_dict(d, "job spec")
    _check_unknown(
        d,
        {"parallelism", "completions", "completionMode", "backoffLimit",
         "suspend", "activeDeadlineSeconds", "template"},
        "job spec",
        strict,
    )
    return t.JobSpec(
        parallelism=d.get("parallelism"),
        completions=d.get("completions"),
        completion_mode=d.get("completionMode"),
        backoff_limit=d.get("backoffLimit", 6),
        suspend=d.get("suspend"),
        active_deadline_seconds=d.get("activeDeadlineSeconds"),
        template=_pod_template_from(d.get("template"), strict),
    )


def _job_template_from(d: Optional[dict], strict: bool) -> t.JobTemplateSpec:
    d = _as_dict(d, "job template")
    _check_unknown(d, {"metadata", "spec"}, "job template", strict)
    meta = _as_dict(d.get("metadata"), "job template metadata")
    return t.JobTemplateSpec(
        labels=dict(meta.get("labels") or {}),
        annotations=dict(meta.get("annotations") or {}),
        spec=_job_spec_from(d.get("spec"), strict),
    )


def _replicated_job_from(d, strict: bool) -> t.ReplicatedJob:
    d = _as_dict(d, "replicatedJobs[] entry")
    _check_unknown(d, {"name", "template", "replicas"}, "replicatedJobs[]", strict)
    if "name" not in d:
        raise SerializationError("replicatedJobs[] entry missing required 'name'")
    return t.ReplicatedJob(
        name=d["name"],
        template=_job_template_from(d.get("template"), strict),
        replicas=_as_int(d, "replicas", 1, "replicatedJobs[]"),
    )


def _spec_from(d: Optional[dict], strict: bool) -> t.JobSetSpec:
    d = _as_dict(d, "spec")
    _check_unknown(
        d,
        {"replicatedJobs", "network", "successPolicy", "failurePolicy",
         "startupPolicy", "suspend", "coordinator", "managedBy",
         "ttlSecondsAfterFinished", "queueName", "priority"},
        "spec",
        strict,
    )
    spec = t.JobSetSpec(
        replicated_jobs=[
            _replicated_job_from(x, strict)
            for x in _as_list(d.get("replicatedJobs"), "spec.replicatedJobs")
        ],
        suspend=d.get("suspend"),
        managed_by=d.get("managedBy"),
        ttl_seconds_after_finished=d.get("ttlSecondsAfterFinished"),
        queue_name=d.get("queueName"),
        priority=d.get("priority"),
    )
    if d.get("network") is not None:
        n = _as_dict(d["network"], "spec.network")
        _check_unknown(
            n,
            {"enableDNSHostnames", "subdomain", "publishNotReadyAddresses"},
            "spec.network", strict,
        )
        spec.network = t.Network(
            enable_dns_hostnames=n.get("enableDNSHostnames"),
            subdomain=n.get("subdomain", ""),
            publish_not_ready_addresses=n.get("publishNotReadyAddresses"),
        )
    if d.get("successPolicy") is not None:
        sp = _as_dict(d["successPolicy"], "spec.successPolicy")
        _check_unknown(sp, {"operator", "targetReplicatedJobs"},
                       "spec.successPolicy", strict)
        spec.success_policy = t.SuccessPolicy(
            operator=sp.get("operator", "All"),
            target_replicated_jobs=list(sp.get("targetReplicatedJobs") or []),
        )
    if d.get("failurePolicy") is not None:
        fp = _as_dict(d["failurePolicy"], "spec.failurePolicy")
        _check_unknown(fp, {"maxRestarts", "rules"}, "spec.failurePolicy", strict)
        rules = []
        for r in _as_list(fp.get("rules"), "spec.failurePolicy.rules"):
            r = _as_dict(r, "failurePolicy rule")
            _check_unknown(
                r,
                {"name", "action", "onJobFailureReasons", "targetReplicatedJobs"},
                "failurePolicy rule", strict,
            )
            rules.append(t.FailurePolicyRule(
                name=r.get("name", ""),
                action=r.get("action", "RestartJobSet"),
                on_job_failure_reasons=list(r.get("onJobFailureReasons") or []),
                target_replicated_jobs=list(r.get("targetReplicatedJobs") or []),
            ))
        spec.failure_policy = t.FailurePolicy(
            max_restarts=_as_int(fp, "maxRestarts", 0, "spec.failurePolicy"), rules=rules
        )
    if d.get("startupPolicy") is not None:
        sp = _as_dict(d["startupPolicy"], "spec.startupPolicy")
        _check_unknown(sp, {"startupPolicyOrder"}, "spec.startupPolicy", strict)
        spec.startup_policy = t.StartupPolicy(
            startup_policy_order=sp.get("startupPolicyOrder", "AnyOrder")
        )
    if d.get("coordinator") is not None:
        c = _as_dict(d["coordinator"], "spec.coordinator")
        _check_unknown(c, {"replicatedJob", "jobIndex", "podIndex"},
                       "spec.coordinator", strict)
        spec.coordinator = t.Coordinator(
            replicated_job=c.get("replicatedJob", ""),
            job_index=_as_int(c, "jobIndex", 0, "spec.coordinator"),
            pod_index=_as_int(c, "podIndex", 0, "spec.coordinator"),
        )
    return spec


def from_dict(d: dict, strict: bool = False) -> t.JobSet:
    """Build a `JobSet` from a k8s-shaped manifest dict."""
    if not isinstance(d, dict):
        raise SerializationError(f"manifest must be a mapping, got {type(d).__name__}")
    api_version = d.get("apiVersion", API_VERSION)
    kind = d.get("kind", KIND)
    if kind != KIND:
        raise SerializationError(f"kind must be {KIND!r}, got {kind!r}")
    if strict and api_version != API_VERSION:
        raise SerializationError(
            f"apiVersion must be {API_VERSION!r}, got {api_version!r}"
        )
    _check_unknown(d, {"apiVersion", "kind", "metadata", "spec", "status"},
                   "JobSet", strict)
    js = t.JobSet(
        metadata=_meta_from(d.get("metadata"), strict),
        spec=_spec_from(d.get("spec"), strict),
    )
    if d.get("status") is not None:
        js.status = status_from_dict(_as_dict(d["status"], "status"), strict=strict)
    return js


def status_from_dict(d: dict, strict: bool = False) -> t.JobSetStatus:
    """Inverse of `status_to_dict` (used by the client SDK to surface the
    status subresource the server reports)."""
    _check_unknown(
        d,
        {"restarts", "restartsCountTowardsMax", "terminalState", "conditions",
         "replicatedJobsStatus"},
        "status", strict,
    )
    for c in _as_list(d.get("conditions"), "status.conditions"):
        _check_unknown(
            _as_dict(c, "status.conditions[]"),
            {"type", "status", "reason", "message", "lastTransitionTime"},
            "status.conditions[]", strict,
        )
    for r in _as_list(d.get("replicatedJobsStatus"), "status.replicatedJobsStatus"):
        _check_unknown(
            _as_dict(r, "status.replicatedJobsStatus[]"),
            {"name", "ready", "succeeded", "failed", "active", "suspended"},
            "status.replicatedJobsStatus[]", strict,
        )
    return t.JobSetStatus(
        restarts=_as_int(d, "restarts", 0, "status"),
        restarts_count_towards_max=_as_int(d, "restartsCountTowardsMax", 0, "status"),
        terminal_state=d.get("terminalState") or "",
        conditions=[
            t.Condition(
                type=c.get("type", ""),
                status=c.get("status", ""),
                reason=c.get("reason", ""),
                message=c.get("message", ""),
            )
            for c in _as_list(d.get("conditions"), "status.conditions")
        ],
        replicated_jobs_status=[
            t.ReplicatedJobStatus(
                name=r.get("name", ""),
                ready=_as_int(r, "ready", 0, "status.replicatedJobsStatus"),
                succeeded=_as_int(r, "succeeded", 0, "status.replicatedJobsStatus"),
                failed=_as_int(r, "failed", 0, "status.replicatedJobsStatus"),
                active=_as_int(r, "active", 0, "status.replicatedJobsStatus"),
                suspended=_as_int(r, "suspended", 0, "status.replicatedJobsStatus"),
            )
            for r in _as_list(d.get("replicatedJobsStatus"), "status.replicatedJobsStatus")
        ],
    )


def from_yaml(text: str, strict: bool = False) -> t.JobSet:
    return from_dict(yaml.safe_load(text), strict=strict)


def load_all(text: str, strict: bool = False) -> list[t.JobSet]:
    """Load every JobSet document from a multi-doc YAML stream, skipping
    non-JobSet documents (k8s manifests commonly interleave kinds)."""
    out = []
    for doc in yaml.safe_load_all(text):
        if isinstance(doc, dict) and doc.get("kind") == KIND:
            out.append(from_dict(doc, strict=strict))
    return out


# ---------------------------------------------------------------------------
# to_dict
# ---------------------------------------------------------------------------


def _prune(d: dict) -> dict:
    """Drop None values and empty containers, k8s omitempty style."""
    return {k: v for k, v in d.items() if v is not None and v != {} and v != [] and v != ""}


def _pod_spec_dict(p: t.PodSpec) -> dict:
    workload = copy.deepcopy(p.workload)
    out = _prune({
        "restartPolicy": p.restart_policy,
        "nodeSelector": dict(p.node_selector),
        "tolerations": [
            _prune({"key": x.key, "operator": x.operator, "value": x.value,
                    "effect": x.effect})
            for x in p.tolerations
        ],
        "affinity": _affinity_dict(p.affinity),
        "subdomain": p.subdomain,
        "hostname": p.hostname,
        "schedulingGates": [{"name": g} for g in p.scheduling_gates],
        "nodeName": p.node_name,
    })
    # Emit preserved k8s container fields at their native positions...
    for k in ("containers", "initContainers", "volumes"):
        if k in workload:
            out[k] = workload.pop(k)
    # ...and whatever remains of the opaque payload under the vendor key.
    if workload:
        out[WORKLOAD_KEY] = workload
    return out


def _pod_template_dict(pt: t.PodTemplateSpec) -> dict:
    meta = _prune({"labels": dict(pt.labels), "annotations": dict(pt.annotations)})
    out = {}
    if meta:
        out["metadata"] = meta
    spec = _pod_spec_dict(pt.spec)
    if spec:
        out["spec"] = spec
    return out


def _job_spec_dict(j: t.JobSpec) -> dict:
    return _prune({
        "parallelism": j.parallelism,
        "completions": j.completions,
        "completionMode": j.completion_mode,
        "backoffLimit": j.backoff_limit if j.backoff_limit != 6 else None,
        "suspend": j.suspend,
        "activeDeadlineSeconds": j.active_deadline_seconds,
        "template": _pod_template_dict(j.template) or None,
    })


def _job_template_dict(jt: t.JobTemplateSpec) -> dict:
    meta = _prune({"labels": dict(jt.labels), "annotations": dict(jt.annotations)})
    out = {}
    if meta:
        out["metadata"] = meta
    spec = _job_spec_dict(jt.spec)
    if spec:
        out["spec"] = spec
    return out


def to_dict(js: t.JobSet, include_status: bool = False) -> dict:
    spec: dict[str, Any] = {
        "replicatedJobs": [
            _prune({
                "name": r.name,
                "replicas": r.replicas,
                "template": _job_template_dict(r.template) or None,
            })
            for r in js.spec.replicated_jobs
        ],
    }
    if js.spec.network is not None:
        n = js.spec.network
        spec["network"] = _prune({
            "enableDNSHostnames": n.enable_dns_hostnames,
            "subdomain": n.subdomain,
            "publishNotReadyAddresses": n.publish_not_ready_addresses,
        })
    if js.spec.success_policy is not None:
        sp = js.spec.success_policy
        spec["successPolicy"] = _prune({
            "operator": sp.operator,
            "targetReplicatedJobs": list(sp.target_replicated_jobs),
        })
    if js.spec.failure_policy is not None:
        fp = js.spec.failure_policy
        spec["failurePolicy"] = _prune({
            "maxRestarts": fp.max_restarts or None,
            "rules": [
                _prune({
                    "name": r.name,
                    "action": r.action,
                    "onJobFailureReasons": list(r.on_job_failure_reasons),
                    "targetReplicatedJobs": list(r.target_replicated_jobs),
                })
                for r in fp.rules
            ],
        })
        if not spec["failurePolicy"]:
            spec["failurePolicy"] = {"maxRestarts": 0}
    if js.spec.startup_policy is not None:
        spec["startupPolicy"] = {
            "startupPolicyOrder": js.spec.startup_policy.startup_policy_order
        }
    if js.spec.coordinator is not None:
        c = js.spec.coordinator
        spec["coordinator"] = _prune({
            "replicatedJob": c.replicated_job,
            "jobIndex": c.job_index or None,
            "podIndex": c.pod_index or None,
        })
    if js.spec.suspend is not None:
        spec["suspend"] = js.spec.suspend
    if js.spec.managed_by is not None:
        spec["managedBy"] = js.spec.managed_by
    if js.spec.ttl_seconds_after_finished is not None:
        spec["ttlSecondsAfterFinished"] = js.spec.ttl_seconds_after_finished
    if js.spec.queue_name is not None:
        spec["queueName"] = js.spec.queue_name
    if js.spec.priority is not None:
        spec["priority"] = js.spec.priority

    out = {
        "apiVersion": API_VERSION,
        "kind": KIND,
        "metadata": _prune({
            "name": js.metadata.name,
            "generateName": js.metadata.generate_name or None,
            "namespace": js.metadata.namespace if js.metadata.namespace != "default" else None,
            "uid": js.metadata.uid,
            "labels": dict(js.metadata.labels),
            "annotations": dict(js.metadata.annotations),
        }),
        "spec": spec,
    }
    if include_status:
        out["status"] = status_to_dict(js.status)
    return out


def status_to_dict(s: t.JobSetStatus) -> dict:
    return _prune({
        "restarts": s.restarts or None,
        "restartsCountTowardsMax": s.restarts_count_towards_max or None,
        "terminalState": s.terminal_state,
        "conditions": [
            _prune({
                "type": c.type,
                "status": c.status,
                "reason": c.reason,
                "message": c.message,
            })
            for c in s.conditions
        ],
        "replicatedJobsStatus": [
            {
                "name": r.name,
                "ready": r.ready,
                "succeeded": r.succeeded,
                "failed": r.failed,
                "active": r.active,
                "suspended": r.suspended,
            }
            for r in s.replicated_jobs_status
        ],
    })


def to_yaml(js: t.JobSet, include_status: bool = False) -> str:
    return yaml.safe_dump(
        to_dict(js, include_status=include_status), sort_keys=False, default_flow_style=False
    )


# ---------------------------------------------------------------------------
# Strict-CRD export (kubectl-apply interop with the reference operator)
# ---------------------------------------------------------------------------

# Annotation carrying the JSON-encoded workload payload in strict-CRD
# manifests (annotations are free-form strings under any CRD schema; a
# vendor pod-SPEC field would be pruned/rejected by server-side field
# validation).
WORKLOAD_ANNOTATION = WORKLOAD_KEY

# The container a strict-CRD manifest runs per pod: this framework's own
# per-pod worker entrypoint, so the exported JobSet is actually RUNNABLE
# under the reference operator, not just schema-valid.
DEFAULT_RUNNER_IMAGE = "ghcr.io/jobset-tpu/runner:latest"


def to_k8s_dict(js: t.JobSet, runner_image: str = DEFAULT_RUNNER_IMAGE) -> dict:
    """Export a manifest that passes the REFERENCE operator's CRD schema
    under strict (server-side) field validation
    (reference: config/components/crd/bases/jobset.x-k8s.io_jobsets.yaml):

    * the opaque workload payload moves from the vendor pod-spec key to a
      pod-template ANNOTATION (JSON-encoded) — `from_dict` transparently
      restores it, so the export round-trips losslessly;
    * pod specs without containers get this framework's worker-entrypoint
      container (`jobset-tpu worker`), satisfying the embedded batch/v1
      JobSpec schema's required `containers` and making the manifest
      runnable on a real cluster.

    Validated strictly against the reference CRD in
    tests/test_crd_interop.py.
    """
    import json as _json

    doc = to_dict(js)
    # The reference CRD has no queue plane: export the admission-queue
    # fields as vendor annotations (free-form under any CRD schema) so a
    # queued JobSet still passes the reference's strict field validation.
    spec_doc = doc.get("spec", {})
    for wire_key, ann_key in (
        ("queueName", "tpu.jobset.x-k8s.io/queue-name"),
        ("priority", "tpu.jobset.x-k8s.io/priority"),
    ):
        value = spec_doc.pop(wire_key, None)
        if value is not None:
            doc.setdefault("metadata", {}).setdefault("annotations", {})[
                ann_key
            ] = str(value)
    for rj in doc.get("spec", {}).get("replicatedJobs", []):
        tmpl = rj.get("template", {}).get("spec", {}).get("template")
        if tmpl is None:
            tmpl = rj.setdefault("template", {}).setdefault(
                "spec", {}
            ).setdefault("template", {})
        spec = tmpl.setdefault("spec", {})
        workload = spec.pop(WORKLOAD_KEY, None)
        if workload:
            ann = tmpl.setdefault("metadata", {}).setdefault("annotations", {})
            ann[WORKLOAD_ANNOTATION] = _json.dumps(workload, sort_keys=True)
        if not spec.get("containers"):
            spec["containers"] = [{
                "name": "worker",
                "image": runner_image,
                "command": ["jobset-tpu", "worker"],
            }]
    return doc


def to_k8s_yaml(js: t.JobSet, runner_image: str = DEFAULT_RUNNER_IMAGE) -> str:
    return yaml.safe_dump(
        to_k8s_dict(js, runner_image=runner_image),
        sort_keys=False, default_flow_style=False,
    )
