"""Pure validation of JobSet specs (create + update).

Mirrors the reference admission validation
(`pkg/webhooks/jobset_webhook.go:155-373`): DNS-1035 length math on generated
job/pod names, subdomain validity, managedBy domain-prefixed-path rules,
success/failure-policy cross-references, rule-name regex + uniqueness,
coordinator bounds, and update immutability (replicatedJobs/managedBy
immutable except the Kueue-mutable pod-template fields while suspended).

All functions return a list of error strings (empty == valid).
"""

from __future__ import annotations

import re
from typing import Optional

from . import keys
from .types import FailurePolicy, JobSet
from ..placement.naming import gen_job_name, gen_pod_name

MAX_MANAGED_BY_LENGTH = 63

# \Z (not $) so a trailing newline can't sneak past validation.
DNS1035_RE = re.compile(r"^[a-z]([-a-z0-9]*[a-z0-9])?\Z")
DNS1123_LABEL_RE = re.compile(r"^[a-z0-9]([-a-z0-9]*[a-z0-9])?\Z")
DNS1123_SUBDOMAIN_RE = re.compile(
    r"^[a-z0-9]([-a-z0-9]*[a-z0-9])?(\.[a-z0-9]([-a-z0-9]*[a-z0-9])?)*\Z"
)
HTTP_PATH_SEGMENT_RE = re.compile(r"^[A-Za-z0-9/\-._~%!$&'()*+,;=:]+\Z")

MIN_RULE_NAME_LENGTH = 1
MAX_RULE_NAME_LENGTH = 128
# Rule names: start alphabetic, middle alphanumeric or `_,:`, end
# alphanumeric or `_` (jobset_webhook.go:288-292).
RULE_NAME_RE = re.compile(r"^[A-Za-z]([A-Za-z0-9_,:]*[A-Za-z0-9_])?\Z")

JOB_NAME_TOO_LONG_MSG = (
    "JobSet name is too long, job names generated for this JobSet "
    "will exceed 63 characters"
)
POD_NAME_TOO_LONG_MSG = (
    "JobSet name is too long, pod names generated for this JobSet "
    "will exceed 63 characters"
)
SUBDOMAIN_TOO_LONG_MSG = (
    ".spec.network.subdomain is too long, must be less than 63 characters"
)


def is_dns1035_label(value: str) -> list[str]:
    errs = []
    if len(value) > 63:
        errs.append("must be no more than 63 characters")
    if not DNS1035_RE.match(value):
        errs.append(
            "a DNS-1035 label must consist of lower case alphanumeric characters "
            "or '-', start with an alphabetic character, and end with an "
            f"alphanumeric character (got {value!r})"
        )
    return errs


def is_dns1123_subdomain(value: str) -> list[str]:
    errs = []
    if len(value) > 253:
        errs.append("must be no more than 253 characters")
    if not DNS1123_SUBDOMAIN_RE.match(value):
        errs.append(
            "a lowercase RFC 1123 subdomain must consist of lower case "
            "alphanumeric characters, '-' or '.', and must start and end with "
            f"an alphanumeric character (got {value!r})"
        )
    return errs


def is_domain_prefixed_path(value: str) -> list[str]:
    """Valid domain-prefixed path, e.g. `acme.io/foo` (jobset_types.go:125-131)."""
    errs = []
    if "/" not in value:
        errs.append(f"must be a domain-prefixed path (such as 'acme.io/foo'): {value!r}")
        return errs
    prefix, _, path = value.partition("/")
    if prefix:
        errs.extend(is_dns1123_subdomain(prefix))
    else:
        errs.append("prefix part of a domain-prefixed path must be non-empty")
    if not path:
        errs.append("path part of a domain-prefixed path must be non-empty")
    elif not HTTP_PATH_SEGMENT_RE.match(path):
        errs.append(f"path part must contain only valid HTTP path characters: {path!r}")
    return errs


def validate_create(js: JobSet) -> list[str]:
    """Validation at creation time (jobset_webhook.go:158-242).

    Assumes defaults have been applied (success/startup policies non-nil).
    """
    errs: list[str] = []
    valid_rjobs = [rjob.name for rjob in js.spec.replicated_jobs]

    # ReplicatedJob names must be unique. The reference enforces this at the
    # CRD layer via listType=map/listMapKey=name (jobset_types.go:79-80);
    # with no CRD layer here the check lands in create validation.
    seen: set[str] = set()
    for name in valid_rjobs:
        if name in seen:
            errs.append(f"duplicate replicatedJob name '{name}'")
        seen.add(name)

    # Subdomain must be a valid DNS-1123 subdomain AND (since it doubles as a
    # service name) a DNS-1035 label.
    if js.spec.network is not None and js.spec.network.subdomain:
        errs.extend(is_dns1123_subdomain(js.spec.network.subdomain))
        for msg in is_dns1035_label(js.spec.network.subdomain):
            if "no more than 63 characters" in msg:
                msg = SUBDOMAIN_TOO_LONG_MSG
            errs.append(msg)

    # managedBy: domain-prefixed path, <= 63 chars. The reserved built-in
    # controller name is always accepted.
    if js.spec.managed_by is not None:
        manager = js.spec.managed_by
        errs.extend(is_domain_prefixed_path(manager))
        if len(manager) > MAX_MANAGED_BY_LENGTH:
            errs.append(
                f"spec.managedBy: must be no more than {MAX_MANAGED_BY_LENGTH} characters"
            )

    for rjob in js.spec.replicated_jobs:
        parallelism = rjob.template.spec.parallelism or 1
        if parallelism * max(int(rjob.replicas), 0) > 2**31 - 1:
            errs.append(
                "the product of replicas and parallelism must not exceed "
                f"{2**31 - 1} for replicatedJob '{rjob.name}'"
            )

        # Generated job names must be DNS-1035 compliant; use the largest job
        # index, which has the longest name (jobset_webhook.go:203-212).
        longest_job_name = gen_job_name(js.name, rjob.name, max(int(rjob.replicas) - 1, 0))
        for msg in is_dns1035_label(longest_job_name):
            if "no more than 63 characters" in msg:
                msg = JOB_NAME_TOO_LONG_MSG
            errs.append(msg)

        # Generated pod hostnames likewise, including the 5-char random suffix
        # (jobset_webhook.go:214-227).
        is_indexed = rjob.template.spec.completion_mode == keys.COMPLETION_MODE_INDEXED
        if is_indexed and rjob.template.spec.completions is not None:
            max_job_idx = str(max(int(rjob.replicas) - 1, 0))
            max_pod_idx = str(max(int(rjob.template.spec.completions) - 1, 0))
            longest_pod_name = (
                gen_pod_name(js.name, rjob.name, max_job_idx, max_pod_idx) + "-abcde"
            )
            for msg in is_dns1035_label(longest_pod_name):
                if "no more than 63 characters" in msg:
                    msg = POD_NAME_TOO_LONG_MSG
                errs.append(msg)

    if js.spec.success_policy is not None:
        for rjob_name in js.spec.success_policy.target_replicated_jobs:
            if rjob_name not in valid_rjobs:
                errs.append(
                    f"invalid replicatedJob name '{rjob_name}' does not appear "
                    "in .spec.ReplicatedJobs"
                )

    if js.spec.failure_policy is not None:
        errs.extend(validate_failure_policy(js.spec.failure_policy, valid_rjobs))

    if js.spec.coordinator is not None:
        err = validate_coordinator(js)
        if err:
            errs.append(err)

    # Admission-queue fields (queue/ subsystem): the queue name doubles as
    # an API object name, so it must be a DNS-1123 label; priority is an
    # int32 like a k8s PriorityClass value. Type-checked (not assumed)
    # because the serializer stores these verbatim and validation must
    # answer with errors, never raise, on a malformed manifest.
    if js.spec.queue_name is not None:
        if not isinstance(js.spec.queue_name, str) or not js.spec.queue_name:
            errs.append(
                "spec.queueName must be a non-empty string "
                f"(got {js.spec.queue_name!r})"
            )
        elif len(js.spec.queue_name) > 63 or not DNS1123_LABEL_RE.match(
            js.spec.queue_name
        ):
            errs.append(
                "spec.queueName must be a DNS-1123 label "
                f"(got {js.spec.queue_name!r})"
            )
    if js.spec.priority is not None:
        if isinstance(js.spec.priority, bool) or not isinstance(
            js.spec.priority, int
        ):
            errs.append(
                f"spec.priority must be an integer (got {js.spec.priority!r})"
            )
        elif not -(2**31) <= js.spec.priority <= 2**31 - 1:
            errs.append("spec.priority must fit in int32")
    if js.spec.queue_name:
        # The admission plane computes the gang request from the pod
        # templates' workload `resources` payloads; reject non-numeric
        # values here so gang_request never raises mid-interception.
        for rjob in js.spec.replicated_jobs:
            resources = rjob.template.spec.template.spec.workload.get(
                "resources"
            )
            if resources is None:
                continue
            if not isinstance(resources, dict):
                errs.append(
                    f"workload resources of replicatedJob '{rjob.name}' "
                    "must be a mapping of resource -> number"
                )
                continue
            for resource, value in resources.items():
                if isinstance(value, bool) or not isinstance(
                    value, (int, float)
                ):
                    errs.append(
                        f"workload resource {resource!r} of replicatedJob "
                        f"'{rjob.name}' must be a number (got {value!r})"
                    )

    return errs


def validate_failure_policy(
    failure_policy: FailurePolicy, valid_rjobs: list[str]
) -> list[str]:
    """Rule-name length/regex/uniqueness + cross-refs (jobset_webhook.go:296-345)."""
    errs: list[str] = []
    name_to_indices: dict[str, list[int]] = {}
    for index, rule in enumerate(failure_policy.rules):
        name_len = len(rule.name)
        if not (MIN_RULE_NAME_LENGTH <= name_len <= MAX_RULE_NAME_LENGTH):
            errs.append(
                f"invalid failure policy rule name of length {name_len}, the rule "
                f"name must be at least {MIN_RULE_NAME_LENGTH} characters long "
                f"and at most {MAX_RULE_NAME_LENGTH} characters long"
            )
        name_to_indices.setdefault(rule.name, []).append(index)
        if not RULE_NAME_RE.match(rule.name):
            errs.append(
                f"invalid failure policy rule name '{rule.name}', a failure "
                "policy rule name must start with an alphabetic character, "
                "optionally followed by a string of alphanumeric characters or "
                "'_,:', and must end with an alphanumeric character or '_'"
            )
        if rule.action not in keys.FAILURE_POLICY_ACTIONS:
            errs.append(f"invalid failure policy action '{rule.action}'")
        for rjob_name in rule.target_replicated_jobs:
            if rjob_name not in valid_rjobs:
                errs.append(
                    f"invalid replicatedJob name '{rjob_name}' in failure policy "
                    "does not appear in .spec.ReplicatedJobs"
                )
        for reason in rule.on_job_failure_reasons:
            if reason not in keys.VALID_ON_JOB_FAILURE_REASONS:
                errs.append(
                    f"invalid job failure reason '{reason}' in failure policy "
                    "is not a recognized job failure reason"
                )
    for rule_name, indices in name_to_indices.items():
        if len(indices) > 1:
            errs.append(
                f"rule names are not unique, rules with indices {indices} all "
                f"have the same name '{rule_name}'"
            )
    return errs


def validate_coordinator(js: JobSet) -> Optional[str]:
    """Coordinator cross-refs and index bounds (jobset_webhook.go:351-373)."""
    coord = js.spec.coordinator
    assert coord is not None
    rjob = next(
        (r for r in js.spec.replicated_jobs if r.name == coord.replicated_job), None
    )
    if rjob is None:
        return f"coordinator replicatedJob {coord.replicated_job} does not exist"
    if not (0 <= coord.job_index < int(rjob.replicas)):
        return (
            f"coordinator job index {coord.job_index} is invalid for "
            f"replicatedJob {rjob.name}"
        )
    if rjob.template.spec.completion_mode != keys.COMPLETION_MODE_INDEXED:
        return "job for coordinator pod must be indexed completion mode"
    completions = rjob.template.spec.completions
    if completions is None or not (0 <= coord.pod_index < int(completions)):
        return (
            f"coordinator pod index {coord.pod_index} is invalid for "
            f"replicatedJob {coord.replicated_job} job index {coord.job_index}"
        )
    return None


def validate_update(old: JobSet, new: JobSet) -> list[str]:
    """Update immutability (jobset_webhook.go:245-280).

    ReplicatedJobs and managedBy are immutable, except that while the JobSet
    is (or is becoming) suspended, pod-template labels/annotations/
    nodeSelector/tolerations/schedulingGates may be mutated (Kueue/DWS
    integration).  Network, success/failure/startup policies are immutable via
    CRD CEL rules in the reference (jobset_types.go:84-104); enforced here
    alongside the webhook checks.
    """
    errs: list[str] = []

    munged = new.clone()
    if bool(old.spec.suspend) or bool(new.spec.suspend):
        if len(munged.spec.replicated_jobs) == len(old.spec.replicated_jobs):
            for idx, rjob in enumerate(munged.spec.replicated_jobs):
                old_tmpl = old.spec.replicated_jobs[idx].template.spec.template
                tmpl = rjob.template.spec.template
                tmpl.labels = dict(old_tmpl.labels)
                tmpl.annotations = dict(old_tmpl.annotations)
                tmpl.spec.node_selector = dict(old_tmpl.spec.node_selector)
                tmpl.spec.tolerations = list(old_tmpl.spec.tolerations)
                tmpl.spec.scheduling_gates = list(old_tmpl.spec.scheduling_gates)

    if munged.spec.replicated_jobs != old.spec.replicated_jobs:
        errs.append("spec.replicatedJobs: Invalid value: field is immutable")
    if munged.spec.managed_by != old.spec.managed_by:
        errs.append("spec.managedBy: Invalid value: field is immutable")
    # The admission plane keys quota accounting and preemption ordering off
    # these; moving a live workload between queues or priorities would
    # corrupt both (Kueue likewise rejects queue-name changes post-create).
    if munged.spec.queue_name != old.spec.queue_name:
        errs.append("spec.queueName: Invalid value: field is immutable")
    if munged.spec.priority != old.spec.priority:
        errs.append("spec.priority: Invalid value: field is immutable")

    # CEL-immutable fields.
    if munged.spec.network != old.spec.network:
        errs.append("spec.network: Invalid value: field is immutable")
    if munged.spec.success_policy != old.spec.success_policy:
        errs.append("spec.successPolicy: Invalid value: field is immutable")
    if munged.spec.failure_policy != old.spec.failure_policy:
        errs.append("spec.failurePolicy: Invalid value: field is immutable")
    if munged.spec.startup_policy != old.spec.startup_policy:
        errs.append("spec.startupPolicy: Invalid value: field is immutable")
    return errs
