"""JobSet API schema as plain Python dataclasses.

The semantic contract mirrors the reference CRD
(`api/jobset/v1alpha2/jobset_types.go:76-357`): a `JobSet` groups
`ReplicatedJob`s, each of which stamps out `replicas` Jobs from a template;
network identity, coordinator, and the success/failure/startup policies hang
off the spec.  The representation here is deliberately *not* a Kubernetes
object model — specs are lightweight immutable-ish dataclasses consumed by
pure defaulting/validation functions and by the reconcile core; deep-copy
semantics come from `clone()` which round-trips through `dataclasses.replace`
on nested fields.
"""

from __future__ import annotations

import copy
from dataclasses import dataclass, field
from typing import Optional


def _clone(obj):
    return copy.deepcopy(obj)


# ---------------------------------------------------------------------------
# Pod / Job templates (minimal batchv1/corev1 analog surface)
# ---------------------------------------------------------------------------


@dataclass(frozen=True, slots=True)
class Toleration:
    """Analog of corev1.Toleration (only the fields the framework touches).

    Frozen: pod-spec clones on the 15k-node bench's per-pod hot path share
    Toleration instances and copy only the list containers; immutability is
    what makes that sharing safe.
    """

    key: str = ""
    operator: str = "Equal"  # "Equal" | "Exists"
    value: str = ""
    effect: str = ""  # "" | "NoSchedule"

    def matches_taint(self, taint: "Taint") -> bool:
        if self.effect and self.effect != taint.effect:
            return False
        if self.operator == "Exists":
            return self.key == "" or self.key == taint.key
        return self.key == taint.key and self.value == taint.value


@dataclass(slots=True)
class Taint:
    """Analog of corev1.Taint."""

    key: str = ""
    value: str = ""
    effect: str = "NoSchedule"


@dataclass(frozen=True, slots=True)
class AffinityTerm:
    """One required pod (anti-)affinity term over the job-key label.

    A reduced corev1.PodAffinityTerm: the reference only ever injects terms
    whose label selector is over `jobset.sigs.k8s.io/job-key`
    (`pod_mutating_webhook.go:95-135`), so the schema models exactly that —
    match a topology domain where a pod with (or without) the given job-key
    runs.

    Frozen (with the key lists normalized to tuples): affinity clones on the
    per-pod hot path share term instances and copy only the term lists;
    immutability is what makes that sharing safe.
    """

    topology_key: str = ""
    # Pods whose JOB_KEY label is in this sequence satisfy the selector.
    job_key_in: Optional[tuple[str, ...]] = None
    # If true, selector matches any pod carrying a JOB_KEY label
    # (combined with job_key_not_in for the anti-affinity term).
    job_key_exists: bool = False
    job_key_not_in: Optional[tuple[str, ...]] = None

    def __post_init__(self):
        # Accept lists at construction (YAML decode, webhooks) but store
        # tuples so instances are hashable and deeply immutable.
        for f in ("job_key_in", "job_key_not_in"):
            v = getattr(self, f)
            if v is not None and not isinstance(v, tuple):
                object.__setattr__(self, f, tuple(v))


@dataclass(slots=True)
class Affinity:
    pod_affinity: list[AffinityTerm] = field(default_factory=list)
    pod_anti_affinity: list[AffinityTerm] = field(default_factory=list)

    def clone(self) -> "Affinity":
        # Structural sharing: AffinityTerm instances are immutable once built
        # (webhooks only append new terms to a pod's own lists), so clones
        # share the term objects and copy only the list containers. This is
        # on the per-pod hot path of the 15k-node bench.
        new = object.__new__(Affinity)
        new.pod_affinity = list(self.pod_affinity)
        new.pod_anti_affinity = list(self.pod_anti_affinity)
        return new


@dataclass(slots=True)
class PodSpec:
    """Reduced corev1.PodSpec carrying the fields the framework reads/writes."""

    restart_policy: str = ""  # defaulted to OnFailure by admission
    node_selector: dict[str, str] = field(default_factory=dict)
    tolerations: list[Toleration] = field(default_factory=list)
    affinity: Optional[Affinity] = None
    subdomain: str = ""
    hostname: str = ""
    scheduling_gates: list[str] = field(default_factory=list)
    node_name: str = ""  # set by the scheduler when bound
    # Opaque workload payload: what the pod "runs" (used by the runtime layer
    # to launch the JAX worker; ignored by the control plane).
    workload: dict = field(default_factory=dict)

    def clone(self) -> "PodSpec":
        # Hand-written clone: generic deepcopy of pod specs was the hottest
        # item in the 15k-node bench profile (the Job controller stamps out
        # one spec per pod). Bypasses dataclass __init__ and shares immutable
        # members (Toleration instances are never mutated in place — callers
        # replace or re-list them); only the mutable containers and the
        # free-form `workload` get copied.
        new = object.__new__(PodSpec)
        new.restart_policy = self.restart_policy
        new.node_selector = dict(self.node_selector)
        new.tolerations = list(self.tolerations)
        new.affinity = (
            self.affinity.clone() if self.affinity is not None else None
        )
        new.subdomain = self.subdomain
        new.hostname = self.hostname
        new.scheduling_gates = list(self.scheduling_gates)
        new.node_name = self.node_name
        new.workload = copy.deepcopy(self.workload) if self.workload else {}
        return new


@dataclass(slots=True)
class PodTemplateSpec:
    labels: dict[str, str] = field(default_factory=dict)
    annotations: dict[str, str] = field(default_factory=dict)
    spec: PodSpec = field(default_factory=PodSpec)

    def clone(self) -> "PodTemplateSpec":
        return PodTemplateSpec(
            labels=dict(self.labels),
            annotations=dict(self.annotations),
            spec=self.spec.clone(),
        )


@dataclass(slots=True)
class JobSpec:
    """Reduced batchv1.JobSpec."""

    parallelism: Optional[int] = None
    completions: Optional[int] = None
    completion_mode: Optional[str] = None  # "Indexed" | "NonIndexed"
    backoff_limit: int = 6
    suspend: Optional[bool] = None
    active_deadline_seconds: Optional[int] = None
    template: PodTemplateSpec = field(default_factory=PodTemplateSpec)

    def pods_expected(self) -> int:
        """min(parallelism, completions) — the single definition of a job's
        expected pod count, shared by status math, placement capacity, pod
        creation and rank assignment (jobset_controller.go:340-350)."""
        parallelism = self.parallelism if self.parallelism is not None else 1
        if self.completions is not None and self.completions < parallelism:
            return self.completions
        return parallelism

    def clone(self) -> "JobSpec":
        return JobSpec(
            parallelism=self.parallelism,
            completions=self.completions,
            completion_mode=self.completion_mode,
            backoff_limit=self.backoff_limit,
            suspend=self.suspend,
            active_deadline_seconds=self.active_deadline_seconds,
            template=self.template.clone(),
        )


@dataclass(slots=True)
class JobTemplateSpec:
    """Analog of batchv1.JobTemplateSpec (metadata + spec)."""

    labels: dict[str, str] = field(default_factory=dict)
    annotations: dict[str, str] = field(default_factory=dict)
    spec: JobSpec = field(default_factory=JobSpec)


# ---------------------------------------------------------------------------
# JobSet spec types (jobset_types.go:217-357)
# ---------------------------------------------------------------------------


@dataclass(slots=True)
class ReplicatedJob:
    """`replicas` Jobs stamped from one template; job names are
    `<jobset>-<name>-<jobIdx>` (jobset_types.go:217-228)."""

    name: str
    template: JobTemplateSpec = field(default_factory=JobTemplateSpec)
    replicas: int = 1


@dataclass(slots=True)
class Network:
    """DNS config (jobset_types.go:230-247): pod hostnames are
    `<jobset>-<rjob>-<jobIdx>-<podIdx>.<subdomain>`."""

    enable_dns_hostnames: Optional[bool] = None
    subdomain: str = ""
    publish_not_ready_addresses: Optional[bool] = None


@dataclass(slots=True)
class SuccessPolicy:
    """Operator All/Any over target replicated jobs (jobset_types.go:312-322)."""

    operator: str = "All"
    target_replicated_jobs: list[str] = field(default_factory=list)


@dataclass(slots=True)
class FailurePolicyRule:
    """First-match rule: (failure reason, parent rjob) -> action
    (jobset_types.go:283-310)."""

    name: str = ""
    action: str = "RestartJobSet"
    on_job_failure_reasons: list[str] = field(default_factory=list)
    target_replicated_jobs: list[str] = field(default_factory=list)


@dataclass(slots=True)
class FailurePolicy:
    max_restarts: int = 0
    rules: list[FailurePolicyRule] = field(default_factory=list)


@dataclass(slots=True)
class StartupPolicy:
    startup_policy_order: str = "AnyOrder"  # "AnyOrder" | "InOrder"


@dataclass(slots=True)
class Coordinator:
    """Which pod is the coordinator; its stable endpoint is stamped on all
    jobs/pods (jobset_types.go:345-357)."""

    replicated_job: str = ""
    job_index: int = 0
    pod_index: int = 0


@dataclass(slots=True)
class JobSetSpec:
    replicated_jobs: list[ReplicatedJob] = field(default_factory=list)
    network: Optional[Network] = None
    success_policy: Optional[SuccessPolicy] = None
    failure_policy: Optional[FailurePolicy] = None
    startup_policy: Optional[StartupPolicy] = None
    suspend: Optional[bool] = None
    coordinator: Optional[Coordinator] = None
    managed_by: Optional[str] = None
    ttl_seconds_after_finished: Optional[int] = None
    # Admission queue (Kueue LocalQueue analog, queue/ subsystem): a named
    # queue makes creation admit-later — the apiserver forces suspend=true
    # and the QueueManager resumes the gang when quota admits it.
    queue_name: Optional[str] = None
    # Workload priority within the admission plane (higher preempts lower;
    # int32 range like a k8s PriorityClass value). Only meaningful with
    # queue_name.
    priority: Optional[int] = None


# ---------------------------------------------------------------------------
# Status types (jobset_types.go:144-190)
# ---------------------------------------------------------------------------


@dataclass(slots=True)
class Condition:
    """Analog of metav1.Condition."""

    type: str = ""
    status: str = "False"  # "True" | "False"
    reason: str = ""
    message: str = ""
    last_transition_time: float = 0.0


@dataclass(slots=True)
class ReplicatedJobStatus:
    name: str = ""
    ready: int = 0
    succeeded: int = 0
    failed: int = 0
    active: int = 0
    suspended: int = 0

    def key(self):
        return (
            self.name,
            self.ready,
            self.succeeded,
            self.failed,
            self.active,
            self.suspended,
        )


@dataclass(slots=True)
class JobSetStatus:
    conditions: list[Condition] = field(default_factory=list)
    restarts: int = 0
    restarts_count_towards_max: int = 0
    terminal_state: str = ""  # "" | "Completed" | "Failed"
    replicated_jobs_status: list[ReplicatedJobStatus] = field(default_factory=list)


# ---------------------------------------------------------------------------
# Object metadata + top-level JobSet
# ---------------------------------------------------------------------------


@dataclass(slots=True)
class ObjectMeta:
    name: str = ""
    # apiserver semantics: when name is empty, the server appends a random
    # 5-char suffix to generate_name at admission (metav1.ObjectMeta).
    generate_name: str = ""
    namespace: str = "default"
    uid: str = ""
    labels: dict[str, str] = field(default_factory=dict)
    annotations: dict[str, str] = field(default_factory=dict)
    creation_time: float = 0.0
    deletion_time: Optional[float] = None
    owner_uid: str = ""  # controller owner reference (single-owner model)


@dataclass(slots=True)
class JobSet:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: JobSetSpec = field(default_factory=JobSetSpec)
    status: JobSetStatus = field(default_factory=JobSetStatus)

    @property
    def name(self) -> str:
        return self.metadata.name

    @property
    def namespace(self) -> str:
        return self.metadata.namespace

    def clone(self) -> "JobSet":
        return _clone(self)


def replicated_job_by_name(js: JobSet, name: str) -> Optional[ReplicatedJob]:
    for rjob in js.spec.replicated_jobs:
        if rjob.name == name:
            return rjob
    return None


def replicated_job_names(js: JobSet) -> list[str]:
    return [rjob.name for rjob in js.spec.replicated_jobs]


def jobset_suspended(js: JobSet) -> bool:
    return bool(js.spec.suspend)


def dns_hostnames_enabled(js: JobSet) -> bool:
    return bool(js.spec.network and js.spec.network.enable_dns_hostnames)


def get_subdomain(js: JobSet) -> str:
    """Subdomain defaults to the JobSet name (jobset_types.go:236-240)."""
    if js.spec.network and js.spec.network.subdomain:
        return js.spec.network.subdomain
    return js.name


def coordinator_endpoint(js: JobSet) -> str:
    """`<js>-<rjob>-<jobIdx>-<podIdx>.<subdomain>` (jobset_controller.go:1032-1036)."""
    c = js.spec.coordinator
    assert c is not None
    return f"{js.name}-{c.replicated_job}-{c.job_index}-{c.pod_index}.{get_subdomain(js)}"


def global_job_index(js: JobSet, replicated_job_name: str, job_idx: int) -> str:
    """Unique index of a job across the whole JobSet: cumulative replicas of
    preceding replicated jobs plus the local index
    (jobset_controller.go:1040-1065)."""
    total = 0
    for rjob in js.spec.replicated_jobs:
        if rjob.name == replicated_job_name:
            return str(total + job_idx)
        total += int(rjob.replicas)
    return ""
