"""Pure defaulting of JobSet specs.

Mirrors the reference admission defaulting (`pkg/webhooks/jobset_webhook.go:105-150`):
success policy All, startup policy AnyOrder, Indexed completion mode, pod
restartPolicy OnFailure, DNS hostnames + publishNotReadyAddresses on, and
failure-policy rule names `failurePolicyRuleN`.  Mutates the JobSet in place
and also returns it (callers that need copy-on-default should `clone()` first).
"""

from __future__ import annotations

from . import keys
from .types import JobSet, Network, StartupPolicy, SuccessPolicy

DEFAULT_RULE_NAME_FMT = "failurePolicyRule{index}"


def apply_defaults(js: JobSet) -> JobSet:
    spec = js.spec

    if spec.success_policy is None:
        spec.success_policy = SuccessPolicy(operator=keys.OPERATOR_ALL)

    if spec.startup_policy is None:
        spec.startup_policy = StartupPolicy(startup_policy_order=keys.STARTUP_ANY_ORDER)

    for rjob in spec.replicated_jobs:
        job_spec = rjob.template.spec
        if job_spec.completion_mode is None:
            job_spec.completion_mode = keys.COMPLETION_MODE_INDEXED
        if job_spec.template.spec.restart_policy == "":
            job_spec.template.spec.restart_policy = keys.RESTART_POLICY_ON_FAILURE
        # k8s defaults parallelism to 1; keep the same observable behavior so
        # ready-count math (min(parallelism, completions)) is well-defined.
        if job_spec.parallelism is None:
            job_spec.parallelism = 1

    if spec.network is None:
        spec.network = Network()
    if spec.network.enable_dns_hostnames is None:
        spec.network.enable_dns_hostnames = True
    if spec.network.publish_not_ready_addresses is None:
        spec.network.publish_not_ready_addresses = True

    if spec.failure_policy is not None:
        for i, rule in enumerate(spec.failure_policy.rules):
            if not rule.name:
                rule.name = DEFAULT_RULE_NAME_FMT.format(index=i)

    return js
