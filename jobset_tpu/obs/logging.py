"""Structured JSON logging stamped with the active trace context.

The reference controller logs through zap in JSON mode (main.go's
`zap.Options`); ours mirrors that with stdlib ``logging`` plus one
formatter that emits a single JSON object per record and joins each
record to the in-process tracer: any log line emitted while a span is
active carries ``trace_id``/``span_id``, so `grep trace_id= logs` and
`GET /debug/traces` meet on the same ids.

Usage::

    configure_json_logging()          # root handler, idempotent
    log = get_logger("jobset_tpu.server")
    log.info("jobset created", extra={"jobset": "default/js"})

Arbitrary ``extra`` keys are carried into the JSON object (standard
LogRecord attributes are excluded), so call sites attach structure
without string formatting.
"""

from __future__ import annotations

import json
import logging
import time
from typing import Optional

from .trace import current_span

# LogRecord's own attribute names — everything else on a record came from
# `extra` and belongs in the JSON payload.
_RESERVED = frozenset(
    logging.LogRecord(
        "", 0, "", 0, "", (), None
    ).__dict__
) | {"message", "asctime", "taskName"}


class JsonLogFormatter(logging.Formatter):
    """One JSON object per line: ts, level, logger, message, trace ids
    (when a span is active), and any `extra` fields."""

    def format(self, record: logging.LogRecord) -> str:
        out = {
            "ts": round(record.created, 6),
            "time": time.strftime(
                "%Y-%m-%dT%H:%M:%S", time.gmtime(record.created)
            )
            + f".{int(record.msecs):03d}Z",
            "level": record.levelname,
            "logger": record.name,
            "message": record.getMessage(),
        }
        active = current_span()
        if active is not None:
            out["trace_id"] = active.context.trace_id
            out["span_id"] = active.context.span_id
        if record.exc_info and record.exc_info[1] is not None:
            exc = record.exc_info[1]
            out["error"] = f"{type(exc).__name__}: {exc}"[:400]
        for key, value in record.__dict__.items():
            if key in _RESERVED or key.startswith("_"):
                continue
            try:
                json.dumps(value)
                out[key] = value
            except (TypeError, ValueError):
                out[key] = repr(value)[:200]
        return json.dumps(out)


_configured = False


def configure_json_logging(
    level: int = logging.INFO, stream=None, force: bool = False
) -> logging.Handler:
    """Install one JSON handler on the ``jobset_tpu`` logger subtree.

    Scoped to the package logger (not root) so embedding applications and
    the test runner keep their own formatting; idempotent unless
    ``force``."""
    global _configured
    pkg_logger = logging.getLogger("jobset_tpu")
    if _configured and not force:
        for h in pkg_logger.handlers:
            if isinstance(h.formatter, JsonLogFormatter):
                return h
    if force:
        # Replace, don't stack: a second JSON handler would double every
        # record.
        for h in list(pkg_logger.handlers):
            if isinstance(h.formatter, JsonLogFormatter):
                pkg_logger.removeHandler(h)
    handler = logging.StreamHandler(stream)
    handler.setFormatter(JsonLogFormatter())
    pkg_logger.addHandler(handler)
    pkg_logger.setLevel(level)
    pkg_logger.propagate = False
    _configured = True
    return handler


def get_logger(name: Optional[str] = None) -> logging.Logger:
    return logging.getLogger(name or "jobset_tpu")
