"""Postmortem debug bundles: one command captures everything an operator
(or a bug report) needs to reconstruct what the control plane was doing.

``write_bundle(client, path)`` scrapes a live controller over its public
API — no privileged side channel, so it works against any reachable
controller — and writes one ``.tgz``:

* ``manifest.json``   — bundle format version, capture time, server URL,
  member list (the loader validates against this);
* ``health.json``     — the aggregated `/debug/health` verdict, including
  the config block and store/WAL stats;
* ``slo.json``        — `/debug/slo` percentile summary;
* ``traces.json``     — the tracer's full finished-trace ring;
* ``events.json``     — every retained cluster event;
* ``jobsets.json``    — every JobSet manifest (status included);
* ``timelines.json``  — one flight-recorder timeline per JobSet, keyed
  ``namespace/name``;
* ``tsdb.json``       — the telemetry plane's full series dump
  (``{"enabled": false}`` when the controller runs without
  ``--telemetry``);
* ``alerts.json``     — alert rules, active alerts, and the transition
  log (same ``enabled`` convention);
* ``profile.json``    — the continuous profiler's hotspot/lock/JIT
  snapshot from `/debug/profile` (``{"enabled": false}`` without
  ``--profile``);
* ``metrics.prom``    — a raw Prometheus text scrape.

``load_bundle(path)`` round-trips the tarball back into a dict of parsed
members (JSON members decoded, ``metrics.prom`` as text) — the loader the
acceptance test drives, and the entry point for offline analysis tools.
"""

from __future__ import annotations

import io
import json
import tarfile
import time

BUNDLE_FORMAT = 1
# Semantic bundle-content version stamped into the manifest. Major bumps
# mean a consumer written against this module cannot safely parse the
# members (load_bundle REJECTS unknown majors — the policy plane's corpus
# builder needs a stable contract across controller generations); minor
# bumps are additive (1.1 added per-timeline `placements` records; 1.2
# added the manifest `lint` block; 1.3 added the race-rule counts
# (RACE001-003) and per-rule `timingMs` inside that block — the race-
# detection plane's debt is now part of every postmortem; 1.4 added
# `tsdb.json` + `alerts.json`, the telemetry plane's full snapshot and
# alert state/transition log, `{"enabled": false}` when the controller
# runs without --telemetry; 1.5 added `profile.json`, the continuous
# profiler's hotspot/lock/JIT snapshot, same `enabled` convention for
# controllers running without --profile).
# Bundles written before the stamp existed are treated as "1.0".
BUNDLE_SCHEMA_VERSION = "1.5"

_JSON_MEMBERS = (
    "manifest.json",
    "health.json",
    "slo.json",
    "traces.json",
    "events.json",
    "jobsets.json",
    "timelines.json",
    "tsdb.json",
    "alerts.json",
    "profile.json",
)


def _lint_block() -> dict:
    """`jobset-tpu lint --stats` counts for the manifest — best-effort."""
    try:
        from ..analysis import lint_stats

        return lint_stats()
    except Exception as exc:  # never fail a postmortem capture over lint
        return {"error": f"{type(exc).__name__}: {exc}"[:200]}


def write_bundle(client, path: str) -> dict:
    """Capture a debug bundle from the controller behind `client` into the
    tarball at `path`. Returns a summary (members, jobset/timeline
    counts). Partial capture is better than none: a JobSet deleted between
    the health snapshot and its timeline fetch is skipped, not fatal."""
    from ..client import ApiError

    health = client.health()
    payloads: dict[str, object] = {
        "health.json": health,
        "slo.json": client.slo_summary(),
        "traces.json": client.traces(limit=0),
        "events.json": client.events(),
    }

    # Telemetry plane (schemaVersion 1.4): the TSDB series dump and the
    # alert state + transition log. A controller running without
    # --telemetry answers 404 on both — the members still exist so
    # consumers can distinguish "telemetry off" from "pre-1.4 bundle".
    for member, fetch in (
        ("tsdb.json", client.tsdb),
        ("alerts.json", client.alerts),
        # Profiling plane (schemaVersion 1.5): hotspot trie + lock-wait +
        # JIT-cache snapshot; 404 means the controller runs without
        # --profile.
        ("profile.json", client.profile),
    ):
        try:
            payloads[member] = {"enabled": True, **fetch()}
        except ApiError as exc:
            if exc.status != 404:
                raise
            payloads[member] = {"enabled": False}

    jobsets: list[dict] = []
    timelines: dict[str, dict] = {}
    for key in health.get("cluster", {}).get("jobsetKeys", []):
        namespace, _, name = key.partition("/")
        try:
            jobsets.append(client.get_raw(name, namespace))
            timelines[key] = client.timeline(name, namespace)
        except ApiError:
            continue  # deleted mid-capture
    payloads["jobsets.json"] = jobsets
    payloads["timelines.json"] = timelines

    metrics_text = client.metrics_text()

    members = sorted([*_JSON_MEMBERS, "metrics.prom"])
    payloads["manifest.json"] = {
        "format": BUNDLE_FORMAT,
        "schemaVersion": BUNDLE_SCHEMA_VERSION,
        "capturedAt": time.strftime(
            # jslint: disable=DET001 capturedAt is operator-facing capture metadata, never replayed or byte-compared
            "%Y-%m-%dT%H:%M:%SZ", time.gmtime()
        ),
        "server": client.base_url,
        "members": members,
        # Lint debt of the CAPTURING build (per-rule visible/suppressed
        # counts, docs/static-analysis.md): postmortems start by asking
        # which contracts the build was already known to bend. Bundles
        # must still capture when the analysis plane itself is broken.
        "lint": _lint_block(),
    }

    with tarfile.open(path, "w:gz") as tar:
        for member in members:
            if member == "metrics.prom":
                data = metrics_text.encode()
            else:
                data = json.dumps(
                    payloads[member], indent=1, sort_keys=True
                ).encode()
            info = tarfile.TarInfo(member)
            info.size = len(data)
            tar.addfile(info, io.BytesIO(data))

    return {
        "path": path,
        "members": members,
        "jobsets": len(jobsets),
        "timelines": len(timelines),
    }


def load_bundle(path: str) -> dict:
    """Parse a debug bundle back into ``{member_name: payload}`` (JSON
    members decoded, ``metrics.prom`` as text). Raises ValueError on a
    tarball that is not a debug bundle or whose manifest disagrees with
    its contents."""
    out: dict[str, object] = {}
    with tarfile.open(path, "r:gz") as tar:
        for member in tar.getmembers():
            fileobj = tar.extractfile(member)
            if fileobj is None:
                continue
            data = fileobj.read()
            if member.name.endswith(".json"):
                out[member.name] = json.loads(data)
            else:
                out[member.name] = data.decode()
    manifest = out.get("manifest.json")
    if not isinstance(manifest, dict) or "members" not in manifest:
        raise ValueError(f"{path!r} is not a debug bundle (no manifest)")
    version = str(manifest.get("schemaVersion", "1.0"))
    major = version.partition(".")[0]
    if major != BUNDLE_SCHEMA_VERSION.partition(".")[0]:
        raise ValueError(
            f"debug bundle {path!r} has schemaVersion {version}; this "
            f"build understands major "
            f"{BUNDLE_SCHEMA_VERSION.partition('.')[0]} "
            f"(current {BUNDLE_SCHEMA_VERSION}) — re-capture the bundle "
            f"with a matching controller"
        )
    missing = [m for m in manifest["members"] if m not in out]
    if missing:
        raise ValueError(
            f"debug bundle {path!r} is missing members {missing}"
        )
    return out
