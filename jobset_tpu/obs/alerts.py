"""Alert state machine + the default rule set for the telemetry plane.

``AlertManager`` runs the declarative alert rules (``obs/rules.py``)
against the embedded TSDB each telemetry tick and drives each
(alertname, labelset) through the Prometheus state machine:

    inactive -> pending (expr true, ``for:`` not yet elapsed)
             -> firing  (expr true for >= ``for:``)
             -> resolved (expr false again) -> inactive

Every transition is appended to a bounded log (``GET /debug/alerts``,
debug bundles), bumps ``jobset_alerts_transitions_total`` /
``jobset_alerts_firing``, and — when a cluster is attached — lands as a
first-class cluster event (kind ``Alert``), so alert flaps interleave
into per-JobSet timelines next to the reconcile/chaos entries that
caused them. A pending alert whose expression goes false before ``for:``
elapses returns to inactive silently (the Prometheus behavior: it never
fired, so there is nothing to resolve).

Transition timestamps come from the telemetry tick's clock — virtual in
simulation, so the whole log is byte-identical across seeded runs (the
chaos teeth in ``chaos/scenarios.py`` assert exactly that).

The default rule set below is the drift-checked source of truth: lint
rule DRF005 (``analysis/rules/drift.py``) fails the tier-1 gate if
docs/observability.md names an alert that does not exist here, or here
gains an alert the docs never mention.
"""

from __future__ import annotations

import threading
from collections import deque

from ..api import keys

STATE_PENDING = "pending"
STATE_FIRING = "firing"
STATE_RESOLVED = "resolved"

# SLO burn-rate objective for the admission-path latency SLO: creation ->
# admission acknowledged within OBJECTIVE_S at TARGET availability. The
# objective snaps up to the enclosing histogram bucket bound (0.256 s on
# the half-power-of-two ladder).
SLO_ADMISSION_OBJECTIVE_S = 0.25
SLO_ADMISSION_TARGET = 0.99

# The default rule set (a plain literal: DRF005 parses it statically).
# Burn-rate alerts follow the SRE-workbook multi-window shape: the fast
# pair (short + long window, high factor) catches cliff burns within a
# minute; the slow pair (longer windows, low factor) catches simmering
# burns without paging on blips. Windows are sized for the sim's 1 s
# virtual ticks and a live controller's 5 s interval alike.
DEFAULT_RULE_SET = {
    "groups": [
        {
            "name": "jobset-telemetry-defaults",
            "rules": [
                {
                    "record": "jobset:flow_rejected:rate1m",
                    "expr": "sum(rate(jobset_flow_rejected_total[60s]))",
                },
                {
                    "record": "jobset:restarts:rate5m",
                    "expr":
                        "sum by (jobset) "
                        "(rate(jobset_restarts_total[300s]))",
                },
                {
                    "record": "jobset:shard_migration_aborts:rate5m",
                    "expr":
                        "sum(rate(jobset_shard_migrations_total"
                        "{outcome=\"abort\"}[300s]))",
                },
                {
                    "alert": "JobSetControlPlaneFailover",
                    "expr": "increase(jobset_ha_failovers_total[300s]) > 0",
                    "for": "0s",
                    "labels": {"severity": "page"},
                    "annotations": {
                        "summary":
                            "a standby replica completed leader failover "
                            "in the last 5m",
                    },
                },
                {
                    "alert": "JobSetFlowShedRateHigh",
                    "expr":
                        "sum(rate(jobset_flow_rejected_total[60s])) > 1",
                    "for": "0s",
                    "labels": {"severity": "ticket"},
                    "annotations": {
                        "summary":
                            "the flow-control plane is shedding more than "
                            "1 req/s (429/watch_busy) over the last minute",
                    },
                },
                {
                    "alert": "JobSetShardQuorumDegraded",
                    "expr":
                        "increase("
                        "jobset_ha_quorum_failures_total[60s]) > 0",
                    "for": "0s",
                    "labels": {"severity": "page"},
                    "annotations": {
                        "summary":
                            "a shard leader failed to reach replication "
                            "quorum in the last minute — a region cut or "
                            "an in-flight replica migration has degraded "
                            "a voting set (see /debug/migrations)",
                    },
                },
                {
                    "alert": "JobSetShardMigrationAborting",
                    "expr":
                        "sum(rate(jobset_shard_migrations_total"
                        "{outcome=\"abort\"}[300s])) > 0",
                    "for": "0s",
                    "labels": {"severity": "ticket"},
                    "annotations": {
                        "summary":
                            "replica migrations are abort-unwinding "
                            "(term fence trips or membership commits "
                            "missing quorum) — the shard plane is "
                            "churning instead of converging",
                    },
                },
                {
                    "alert": "JobSetLockContentionHigh",
                    "expr":
                        "sum by (lock) "
                        "(rate(jobset_lock_wait_seconds_sum[60s])) > 0.2",
                    "for": "0s",
                    "labels": {"severity": "ticket"},
                    "annotations": {
                        "summary":
                            "threads are spending >20% of wall-clock "
                            "waiting on one instrumented lock (continuous "
                            "profiling plane, --profile) — check "
                            "/debug/profile for the holder's hotspots",
                    },
                },
                {
                    "alert": "JobSetSLOAdmissionFastBurn",
                    "expr":
                        "slo_burn_rate(jobset_slo_time_to_admission_seconds"
                        ", 0.25, 0.99, 60s) > 2 and "
                        "slo_burn_rate(jobset_slo_time_to_admission_seconds"
                        ", 0.25, 0.99, 300s) > 2",
                    "for": "0s",
                    "labels": {"severity": "page"},
                    "annotations": {
                        "summary":
                            "admission latency is burning the 99% SLO "
                            "error budget at >2x in both the 1m and 5m "
                            "windows",
                    },
                },
                {
                    "alert": "JobSetSLOAdmissionSlowBurn",
                    "expr":
                        "slo_burn_rate(jobset_slo_time_to_admission_seconds"
                        ", 0.25, 0.99, 600s) > 1 and "
                        "slo_burn_rate(jobset_slo_time_to_admission_seconds"
                        ", 0.25, 0.99, 1800s) > 1",
                    "for": "60s",
                    "labels": {"severity": "ticket"},
                    "annotations": {
                        "summary":
                            "admission latency has burned the 99% SLO "
                            "error budget at >1x for 10m+ (slow burn)",
                    },
                },
            ],
        }
    ]
}


def default_rules():
    """The built-in recording + alert rules (parsed fresh per call so a
    Telemetry instance can mutate its copy without aliasing)."""
    from .rules import load_rules_dict

    return load_rules_dict(DEFAULT_RULE_SET)


def _label_key(labels: dict) -> tuple:
    return tuple(sorted(labels.items()))


class AlertManager:
    """Pending/firing/resolved state per (alertname, labelset), with a
    bounded transition log. Thread-safe: the sampler thread evaluates
    while HTTP handlers read state; effects that take other locks
    (metrics, cluster events) run OUTSIDE the manager lock so it never
    couples into subsystem lock orders."""

    def __init__(self, rules=None, cluster=None,
                 max_transitions: int = 4096):
        self.rules = list(rules or [])
        self.cluster = cluster
        self._active: dict[tuple, dict] = {}  # guarded-by: _lock
        self._transitions: deque = deque(  # guarded-by: _lock
            maxlen=max_transitions
        )
        self._lock = threading.Lock()

    def evaluate(self, tsdb, now: float) -> None:
        from .rules import evaluate as eval_expr

        for rule in self.rules:
            vec = eval_expr(rule.ast, tsdb, now)
            self.observe(rule, vec, now)

    def observe(self, rule, vec, now: float) -> None:
        """Fold one rule's instant-vector result into the state machine.
        A non-empty result means "true" for each labelset it carries."""
        current = {_label_key(labels): (labels, value)
                   for labels, value in vec}
        emitted: list[tuple[str, dict, float | None]] = []
        with self._lock:
            for lkey, (labels, value) in sorted(current.items()):
                key = (rule.name, lkey)
                entry = self._active.get(key)
                if entry is None:
                    state = (STATE_FIRING if rule.for_s <= 0
                             else STATE_PENDING)
                    self._active[key] = {
                        "rule": rule, "labels": dict(labels),
                        "state": state, "since": now, "value": value,
                    }
                    emitted.append((state, dict(labels), value))
                else:
                    entry["value"] = value
                    if (entry["state"] == STATE_PENDING
                            and now - entry["since"] >= rule.for_s):
                        entry["state"] = STATE_FIRING
                        entry["since"] = now
                        emitted.append(
                            (STATE_FIRING, dict(entry["labels"]), value)
                        )
            stale = [
                key for key in self._active
                if key[0] == rule.name and key[1] not in current
            ]
            for key in sorted(stale):
                entry = self._active.pop(key)
                if entry["state"] == STATE_FIRING:
                    emitted.append(
                        (STATE_RESOLVED, dict(entry["labels"]), None)
                    )
                # pending -> inactive: never fired, nothing to resolve.
            for state, labels, value in emitted:
                self._transitions.append({
                    "ts": now,
                    "alert": rule.name,
                    "state": state,
                    "labels": labels,
                })
            still_firing = any(
                key[0] == rule.name
                and entry["state"] == STATE_FIRING
                for key, entry in self._active.items()
            )
        if not emitted:
            return
        from ..core import metrics

        for state, labels, value in emitted:
            metrics.alerts_transitions_total.inc(rule.name, state)
        metrics.alerts_firing.set(1.0 if still_firing else 0.0, rule.name)
        if self.cluster is not None:
            for state, labels, value in emitted:
                etype = (keys.EVENT_WARNING if state == STATE_FIRING
                         else keys.EVENT_NORMAL)
                detail = (
                    "".join(
                        f" {k}={v}" for k, v in sorted(labels.items())
                    )
                    or ""
                )
                self.cluster.record_event(
                    "Alert", rule.name, etype,
                    f"Alert{state.capitalize()}",
                    f"{rule.name} {state} ({rule.expr}){detail}",
                )

    # -- read surface ----------------------------------------------------

    def state(self) -> dict:
        """``GET /debug/alerts`` payload: configured rules, active
        alerts, and the transition log — all deterministically ordered."""
        with self._lock:
            active = [
                {
                    "alert": name,
                    "state": entry["state"],
                    "since": entry["since"],
                    "labels": dict(entry["labels"]),
                    "value": entry["value"],
                }
                for (name, _), entry in sorted(
                    self._active.items(),
                    key=lambda item: (item[0][0], item[0][1]),
                )
            ]
            transitions = list(self._transitions)
        return {
            "rules": [r.to_dict() for r in self.rules],
            "active": active,
            "transitions": transitions,
        }

    def transition_log(self) -> list[dict]:
        with self._lock:
            return list(self._transitions)

    def firing(self) -> list[str]:
        """Names of rules with at least one firing labelset, sorted."""
        with self._lock:
            return sorted({
                key[0] for key, entry in self._active.items()
                if entry["state"] == STATE_FIRING
            })
