"""Embedded telemetry time-series store (the in-process TSDB).

The metrics registry, SLO tracker, and ``/debug/health`` are all
point-in-time snapshots — nothing could answer "what was the write p99
doing in the 30 s before that failover?" or fire on an SLO burn. This
module closes that gap without external dependencies:

* ``TimeSeriesStore`` — a bounded per-series chunk store. Timestamps are
  delta-of-delta encoded and values are Gorilla-style XOR-of-bits
  encoded (``array``-backed, so a sealed 120-sample chunk is two flat
  arrays, not 120 dicts). Retention is a ring of chunks per series: when
  a series exceeds its sample budget the oldest sealed chunk drops
  whole. Value decode is bit-exact; timestamp decode is
  encoder/decoder-lockstep (the encoder advances its own state through
  the reconstructed floats, so decode always reproduces exactly what
  queries saw at append time — deterministic across runs by
  construction).
* ``Telemetry`` — the plane driver: samples the ENTIRE metrics registry
  (``core.metrics.sample_registry()``, including the SLO histograms'
  bucket ladders) on the cluster's injectable clock, then runs the
  recording + alert rules (``obs/rules.py``, ``obs/alerts.py``) against
  the store. Under a ``FakeClock`` every tick is a deterministic,
  seeded-byte-identical function of the cluster's history; under a wall
  clock ``start()`` runs the same tick on a daemon sampler thread.

Everything here is stdlib-only; sampling ~350 series is a few dict ops
and two array appends per series per tick.
"""

from __future__ import annotations

import struct
import threading
import time
from array import array

from ..utils.clock import Clock

# Samples per chunk before it seals and a fresh one opens. 120 samples
# at the default 5 s interval = 10 minutes per chunk, so retention
# trimming (whole-chunk drops) has 10-minute granularity.
CHUNK_SAMPLES = 120

# Default per-series retention in samples (~3.5 h at 5 s interval).
DEFAULT_RETENTION_SAMPLES = 2520


def _bits(v: float) -> int:
    return struct.unpack("<Q", struct.pack("<d", v))[0]


def _unbits(b: int) -> float:
    return struct.unpack("<d", struct.pack("<Q", b))[0]


class Chunk:
    """One sealed-or-open run of samples: first (t, v) stored verbatim,
    then delta-of-delta timestamps (``array('d')``) and XORed value bits
    (``array('Q')``). Append-only; readers iterate a decoded copy."""

    __slots__ = ("t0", "v0", "_dods", "_xors", "_t", "_dt", "_vbits",
                 "count")

    def __init__(self, t: float, v: float):
        self.t0 = t
        self.v0 = v
        self._dods = array("d")
        self._xors = array("Q")
        # Encoder state tracks the RECONSTRUCTED floats (what a decoder
        # will compute), so encode and decode can never drift apart.
        self._t = t
        self._dt = 0.0
        self._vbits = _bits(v)
        self.count = 1

    def append(self, t: float, v: float) -> None:
        dod = (t - self._t) - self._dt
        self._dods.append(dod)
        self._dt += dod
        self._t += self._dt
        bits = _bits(v)
        self._xors.append(bits ^ self._vbits)
        self._vbits = bits
        self.count += 1

    def samples(self) -> list[tuple[float, float]]:
        out = [(self.t0, self.v0)]
        t, dt, vbits = self.t0, 0.0, _bits(self.v0)
        for dod, xor in zip(self._dods, self._xors):
            dt += dod
            t += dt
            vbits ^= xor
            out.append((t, _unbits(vbits)))
        return out

    @property
    def last_t(self) -> float:
        return self._t


class Series:
    __slots__ = ("name", "labels", "born_ts", "chunks", "count")

    def __init__(self, name: str, labels: tuple, born_ts: float):
        self.name = name
        self.labels = labels  # sorted tuple of (label, value) pairs
        self.born_ts = born_ts  # first-ever sample time (birth-from-zero)
        self.chunks: list[Chunk] = []
        self.count = 0

    def append(self, t: float, v: float, retention: int) -> None:
        if not self.chunks or self.chunks[-1].count >= CHUNK_SAMPLES:
            self.chunks.append(Chunk(t, v))
        else:
            self.chunks[-1].append(t, v)
        self.count += 1
        while self.count > retention and len(self.chunks) > 1:
            self.count -= self.chunks.pop(0).count

    def samples(self, start: float | None = None,
                end: float | None = None) -> list[tuple[float, float]]:
        out: list[tuple[float, float]] = []
        for chunk in self.chunks:
            if start is not None and chunk.last_t < start:
                continue
            for t, v in chunk.samples():
                if start is not None and t < start:
                    continue
                if end is not None and t > end:
                    break
                out.append((t, v))
        return out

    def latest(self) -> tuple[float, float] | None:
        if not self.chunks:
            return None
        return self.chunks[-1].samples()[-1]


class TimeSeriesStore:
    """Bounded map of ``(name, labels) -> Series``. Thread-safe: the
    sampler thread appends while HTTP handler threads query."""

    def __init__(self, retention_samples: int = DEFAULT_RETENTION_SAMPLES):
        self.retention_samples = int(retention_samples)
        self._series: dict[tuple, Series] = {}  # guarded-by: _lock
        self._first_ts: float | None = None  # guarded-by: _lock
        self._lock = threading.Lock()

    def append(self, name: str, labels: tuple, t: float, v: float) -> None:
        labels = tuple(sorted(labels))
        key = (name, labels)
        with self._lock:
            if self._first_ts is None:
                self._first_ts = t
            series = self._series.get(key)
            if series is None:
                series = self._series[key] = Series(name, labels, t)
            series.append(t, v, self.retention_samples)

    def series_count(self) -> int:
        with self._lock:
            return len(self._series)

    def sample_count(self) -> int:
        with self._lock:
            return sum(s.count for s in self._series.values())

    def _select(self, name: str, matchers: dict) -> list[Series]:
        with self._lock:
            picked = [
                s for (n, _), s in sorted(self._series.items())
                if n == name
            ]
        if matchers:
            items = set(matchers.items())
            picked = [s for s in picked if items.issubset(set(s.labels))]
        return picked

    # -- query surface (rules engine + /debug/tsdb) ----------------------

    def instant(self, name: str, matchers: dict, now: float,
                lookback: float) -> list[tuple[dict, float]]:
        """Last sample per matching series within the staleness lookback."""
        out = []
        for s in self._select(name, matchers):
            last = s.latest()
            if last is not None and now - lookback <= last[0] <= now:
                out.append((dict(s.labels), last[1]))
        return out

    def window(self, name: str, matchers: dict, now: float,
               window: float) -> list[tuple[dict, list, bool]]:
        """Range selector: per matching series, the samples in
        ``(now-window, now]`` plus a born-in-window flag (a counter
        series that first appeared inside the window implicitly rose
        from 0 — rate()/increase() credit its first value as delta, so
        two seeded runs agree even when one inherits the series from
        earlier process history and the other creates it mid-window).

        A series born on the store's very first sample tick gets NO
        birth credit: its first value is inherited process-global
        registry state (a previous run's accumulation), not growth this
        store witnessed — crediting it would fire delta alerts at t0 of
        every second seeded run."""
        start = now - window
        with self._lock:
            first_ts = self._first_ts
        out = []
        for s in self._select(name, matchers):
            samples = [
                (t, v) for t, v in s.samples(start=start, end=now)
                if t > start
            ]
            if samples:
                born = s.born_ts > start and s.born_ts != first_ts
                out.append((dict(s.labels), samples, born))
        return out

    def snapshot(self, start: float | None = None,
                 end: float | None = None) -> dict:
        """Deterministic JSON-able dump (debug bundles, byte-identity
        tests): series sorted by (name, labels), decoded samples."""
        with self._lock:
            series = sorted(self._series.items())
        return {
            "retentionSamples": self.retention_samples,
            "series": [
                {
                    "name": s.name,
                    "labels": dict(s.labels),
                    "samples": [
                        [t, v] for t, v in s.samples(start=start, end=end)
                    ],
                }
                for _, s in series
            ],
        }


class Telemetry:
    """The telemetry plane: TSDB + rule engine + alert manager, ticked on
    the injectable clock.

    ``tick()`` is the whole plane: sample the registry into the TSDB,
    evaluate recording rules (results append back into the TSDB as
    first-class series), evaluate alert rules into the alert state
    machine. In simulation the harness calls ``tick()`` at script points
    on a ``FakeClock`` — byte-identical across seeded runs. On a live
    controller ``start()`` drives the same tick from a daemon sampler
    thread every ``interval`` wall seconds."""

    def __init__(self, clock: Clock | None = None, interval: float = 5.0,
                 cluster=None, rules_path: str | None = None,
                 retention_samples: int = DEFAULT_RETENTION_SAMPLES,
                 use_default_rules: bool = True):
        from ..core import metrics
        from .alerts import AlertManager, default_rules
        from .rules import load_rules_file

        self.clock = clock or Clock()
        self.interval = float(interval)
        self.tsdb = TimeSeriesStore(retention_samples=retention_samples)
        metrics.telemetry_series.bind(
            self.tsdb, lambda store: store.series_count()
        )
        if rules_path is not None:
            self.recording_rules, alert_rules = load_rules_file(rules_path)
        elif use_default_rules:
            self.recording_rules, alert_rules = default_rules()
        else:
            self.recording_rules, alert_rules = [], []
        self.alerts = AlertManager(rules=alert_rules, cluster=cluster)
        self._tick_lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    @property
    def alert_rules(self):
        return self.alerts.rules

    def tick(self, now: float | None = None) -> None:
        """One sampler pass. Serialized: the sampler thread and any
        synchronous caller (tests, drain paths) must not interleave two
        passes, or rule evals would see half a tick's samples.

        Each stage is error-contained: a raising CallbackGauge provider
        outside collect()'s own containment (the registry sampler path),
        a rule whose expression trips on a malformed series, or an alert
        effect that throws must degrade THAT stage of THIS tick — never
        kill the sampler thread. Contained errors are counted per stage
        in ``jobset_telemetry_tick_errors_total`` so the degradation is
        itself observable (the plane must not fail silently)."""
        from ..core import metrics
        from .rules import evaluate

        if now is None:
            now = self.clock.now()
        with self._tick_lock:
            try:
                samples = metrics.sample_registry()
                for name, labels, value in samples:
                    self.tsdb.append(name, labels, now, value)
                metrics.telemetry_samples_total.inc(
                    amount=float(len(samples))
                )
            except Exception:
                metrics.telemetry_tick_errors_total.inc("sample")
            try:
                for rule in self.recording_rules:
                    for labels, value in evaluate(rule.ast, self.tsdb, now):
                        self.tsdb.append(
                            rule.name, tuple(sorted(labels.items())),
                            now, value,
                        )
            except Exception:
                metrics.telemetry_tick_errors_total.inc("rules")
            try:
                self.alerts.evaluate(self.tsdb, now)
            except Exception:
                metrics.telemetry_tick_errors_total.inc("alerts")
            if self.recording_rules or self.alerts.rules:
                metrics.telemetry_rule_evals_total.inc()

    # -- wall-clock sampler thread (live controllers) --------------------

    def start(self) -> "Telemetry":
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="telemetry-sampler", daemon=True
        )
        self._thread.start()
        return self

    def _run(self) -> None:
        # The "telemetry" phase row is observed HERE, not inside tick():
        # synchronous sim-driven ticks must stay byte-identical across
        # seeded runs, and a perf_counter-valued series sampled into the
        # TSDB on the very next tick would break that contract. Live
        # sampler passes have no such contract.
        while not self._stop.wait(self.interval):
            t0 = time.perf_counter()
            try:
                self.tick()
                from ..core import metrics

                metrics.tick_phase_seconds.observe(
                    time.perf_counter() - t0, "telemetry"
                )
            except Exception:
                # Belt and braces over tick()'s per-stage containment: a
                # failure OUTSIDE the contained stages (the clock itself,
                # a histogram observe) still must not kill the sampler.
                from ..core import metrics

                metrics.telemetry_tick_errors_total.inc("tick")

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.interval + 5.0)
            self._thread = None
