"""Per-JobSet flight-recorder timeline: one ordered, queryable answer to
"what happened to JobSet X and how long did each phase take?".

The assembler is query-time: it does not record anything itself, it
*correlates* what the subsystems already record —

* lifecycle phase marks from the SLO tracker (``obs/slo.py``): created,
  admitted, scheduled (all pods placed), ready, restart/recovery windows;
* JobSet status conditions (suspend/resume, startup policy, terminal);
* cluster ``Event`` records for the JobSet — including the queue plane's
  admission/preemption/requeue decisions and the pump's containment
  events — each stamped with the trace id active at emission, so the
  timeline joins ``GET /debug/traces`` by id, not timestamp heuristics;
* chaos injections from the fault injector's log: faults whose detail
  names this JobSet (or one of its pods/child jobs), plus control-plane-
  wide faults (``solver.*``, ``store.write``) that affect every gang's
  placement/durability, in injected (seq) order;
* the durable store's last commit point covering this JobSet (seq /
  resourceVersion), when ``--data-dir`` is on.

Event/condition/phase entries merge into one time-ordered ``entries``
list (ties broken phase < condition < event, then by event seq — all
deterministic, so a seeded simulation run assembles a byte-identical
timeline). Chaos injections keep their own ``chaos`` list ordered by
injection seq: the injector deliberately records no wall time (its log is
the byte-identity artifact of seeded runs), and seq order IS the injected
order.

Served at ``GET /debug/timeline/{namespace}/{name}`` and rendered by
``jobset-tpu describe jobset NAME``.
"""

from __future__ import annotations

import re
from typing import Optional

# Injection points whose faults are control-plane-wide: not attributable
# to one JobSet by detail string, but material to every gang's placement
# (solver path) or durability (store writes).
_GLOBAL_CHAOS_POINTS = ("solver.", "store.")

_DETAIL_SPLIT = re.compile(r"[\s/]+")

# Merge-order priority for same-instant entries: a phase mark explains the
# condition/event that follows it at the same virtual timestamp.
_SOURCE_ORDER = {"phase": 0, "condition": 1, "event": 2}


def _chaos_matches(detail: str, name: str, child_prefixes) -> bool:
    """Does an injection-log detail string name this JobSet or one of its
    children? Details are namespaced names ("ns/jobset", "ns/pod-name"),
    request lines ("POST /apis/.../jobsets/name"), or addresses. Child
    object names extend a `<jobset>-<replicatedJob>-` prefix — matched
    against the spec's actual replicated-job names so a JobSet named
    "train" never claims faults belonging to "train-2"."""
    for token in _DETAIL_SPLIT.split(detail):
        if token == name or any(
            token.startswith(p) for p in child_prefixes
        ):
            return True
    return False


def _entry(
    time: float,
    source: str,
    type_: str,
    reason: str,
    message: str,
    trace_id: str = "",
    seq: int = 0,
) -> dict:
    return {
        "time": round(float(time), 6),
        "source": source,
        "type": type_,
        "reason": reason,
        "message": message,
        "traceId": trace_id or None,
        "seq": seq,
    }


def assemble(
    cluster,
    namespace: str,
    name: str,
    injector=None,
) -> Optional[dict]:
    """Build the timeline for one JobSet, or None when the cluster has
    never heard of it. Caller holds the cluster lock (the server route
    does); the assembly is read-only."""
    js = cluster.get_jobset(namespace, name)
    tracker = getattr(cluster, "slo", None)
    record = (
        tracker.record_for(namespace, name) if tracker is not None else None
    )
    if js is None and record is None:
        return None

    entries: list[dict] = []

    # Phase marks (SLO tracker). A recovered-from-crash cluster has no
    # tracker record for pre-crash JobSets; creation falls back to
    # metadata below and the phases block degrades to nulls.
    if record is not None:
        for mark in record["marks"]:
            entries.append(_entry(
                mark["time"], "phase", mark["phase"], mark["phase"],
                mark["detail"],
            ))
    elif js is not None:
        entries.append(_entry(
            js.metadata.creation_time, "phase", "Created", "Created",
            "jobset created (no lifecycle record: created before this "
            "controller started)",
        ))

    # Status conditions.
    if js is not None:
        for c in js.status.conditions:
            entries.append(_entry(
                c.last_transition_time, "condition", c.type,
                c.reason or c.type,
                f"{c.type}={c.status}"
                + (f": {c.message}" if c.message else ""),
            ))

    # Cluster events for this JobSet (queue decisions, restarts,
    # containment, placement violations all arrive as JobSet events).
    # Namespace-filtered: a legacy event recorded without one ("") still
    # matches, but same-named JobSets in different namespaces never
    # cross-pollute.
    for e in cluster.events:
        if (
            e.object_kind == "JobSet"
            and e.object_name == name
            and e.namespace in ("", namespace)
        ):
            entries.append(_entry(
                e.time, "event", e.type, e.reason, e.message,
                trace_id=e.trace_id, seq=e.seq,
            ))

    entries.sort(
        key=lambda x: (x["time"], _SOURCE_ORDER[x["source"]], x["seq"])
    )

    # Chaos injections, in injected (seq) order.
    if injector is None:
        from ..chaos import get_injector

        injector = get_injector()
    chaos: list[dict] = []
    if injector is not None:
        # Exact child-name prefixes: from the live spec, else from the
        # replicated-job names the lifecycle record preserved past
        # deletion, else (record-less legacy object) the generic
        # "<name>-" heuristic.
        if js is not None:
            child_prefixes = tuple(
                f"{name}-{rjob.name}-"
                for rjob in js.spec.replicated_jobs
            )
        elif record is not None and record.get("rjob_names"):
            child_prefixes = tuple(
                f"{name}-{rjob_name}-"
                for rjob_name in record["rjob_names"]
            )
        else:
            child_prefixes = (f"{name}-",)
        for fault in injector.log_snapshot():
            point = fault["point"]
            if point.startswith(_GLOBAL_CHAOS_POINTS) or _chaos_matches(
                fault["detail"], name, child_prefixes
            ):
                chaos.append({
                    "seq": fault["seq"],
                    "point": point,
                    "kind": fault["kind"],
                    "arrival": fault["arrival"],
                    "detail": fault["detail"],
                })

    # Last durable commit covering this JobSet (store enabled only).
    store = getattr(cluster, "store", None)
    store_commit = None
    if store is not None:
        store_commit = getattr(store, "last_jobset_commit", {}).get(
            f"{namespace}/{name}"
        )

    created_at = (
        record["created_at"] if record is not None
        else (js.metadata.creation_time if js is not None else None)
    )
    phases = {
        "createdAt": created_at,
        "admittedAt": record["admitted_at"] if record else None,
        "scheduledAt": record["scheduled_at"] if record else None,
        "firstReadyAt": record["first_ready_at"] if record else None,
        "restarts": (
            js.status.restarts if js is not None
            else (record["restarts"] if record else 0)
        ),
        "recoveries": record["recoveries"] if record else 0,
        "deletedAt": record.get("deleted_at") if record else None,
        "inRestartOutage": bool(
            record and record["restart_started_at"] is not None
        ),
    }
    for src, dst in (
        ("admittedAt", "timeToAdmissionS"),
        ("scheduledAt", "timeToScheduledS"),
        ("firstReadyAt", "timeToReadyS"),
    ):
        phases[dst] = (
            round(phases[src] - created_at, 6)
            if phases[src] is not None and created_at is not None
            else None
        )

    trace_ids: list[str] = []
    for entry in entries:
        tid = entry["traceId"]
        if tid and tid not in trace_ids:
            trace_ids.append(tid)

    return {
        "namespace": namespace,
        "name": name,
        "uid": (
            js.metadata.uid if js is not None else record["uid"]
        ),
        "deleted": js is None,
        "terminalState": (
            js.status.terminal_state if js is not None else None
        ),
        "phases": phases,
        "entries": entries,
        # Placement decisions with their candidate feature vectors — the
        # learned-policy training signal (policy/dataset.py joins these
        # with the phase marks above into (features, outcome) examples).
        "placements": (
            [
                {**p, "time": round(float(p["time"]), 6)}
                for p in record.get("placements", ())
            ]
            if record is not None else []
        ),
        "chaos": chaos,
        "storeCommit": dict(store_commit) if store_commit else None,
        "traceIds": trace_ids,
    }
