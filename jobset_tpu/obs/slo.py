"""Lifecycle SLO instrumentation: the quantities placement-policy work
optimizes for, measured per JobSet off the flight-recorder phase marks.

Three histograms (registered in ``core/metrics.py`` so the doc-drift lint
covers them) capture the gang lifecycle latencies an operator actually
cares about:

* ``jobset_slo_time_to_admission_seconds`` — creation -> gang admission.
  Queue-managed gangs admit when the QueueManager resumes them; unqueued
  gangs admit at creation (the observation is ~0 — truthful, and it keeps
  the histogram's population meaning "every gang" instead of "gangs that
  happened to be queued").
* ``jobset_slo_time_to_ready_seconds`` — creation -> the first moment
  every replicated job reports all replicas ready (cold time-to-ready).
* ``jobset_slo_restart_recovery_seconds`` — gang restart (failure-policy
  recreate) -> all replicas ready again: the outage window a training job
  experiences. Overlapping restarts before recovery extend ONE window
  (measured from the first unrecovered restart), matching how an operator
  counts downtime.

Time comes from the cluster clock: virtual in simulations (so tests see
deterministic durations), wall time in a live controller.

The tracker is a per-cluster observer (``cluster.slo``) fed by three
hooks — ``on_created`` (Cluster.create_jobset), ``on_admitted``
(QueueManager._admit), ``on_restart``/``on_status`` (the reconciler) —
and keeps one bounded record per JobSet uid. Records double as the
timeline's phase marks (``obs/timeline.py``); they are observability
state, never persisted, and cost a few dict ops per reconcile.
"""

from __future__ import annotations

import math
from collections import OrderedDict
from typing import Optional

# Bounded phase-mark history per record: enough for a long restart history
# without letting a crash-looping gang grow memory.
MAX_MARKS = 64
# Bounded placement-decision history per record (the policy plane's
# training signal): a crash-looping gang keeps its newest decisions.
MAX_PLACEMENTS = 512
# Bounded record population (uids): evicts oldest when exceeded, so a
# create/delete churn workload cannot grow tracker memory.
MAX_RECORDS = 8192


class LifecycleTracker:
    """Per-cluster lifecycle phase tracker; one record per JobSet uid."""

    def __init__(self, clock):
        self.clock = clock
        self.records: "OrderedDict[str, dict]" = OrderedDict()
        self._by_key: dict[tuple[str, str], str] = {}  # (ns, name) -> uid

    # -- hooks (called by the cluster / queue manager / reconciler) -------

    def on_created(self, js, queued: bool) -> None:
        now = self.clock.now()
        uid = js.metadata.uid
        record = {
            "key": (js.metadata.namespace, js.metadata.name),
            "uid": uid,
            # Replicated-job names survive deletion so the timeline's
            # chaos attribution keeps exact child prefixes even for the
            # postmortem (spec-gone) path.
            "rjob_names": [r.name for r in js.spec.replicated_jobs],
            "created_at": now,
            "queued": queued,
            "admitted_at": None,
            "scheduled_at": None,
            "first_ready_at": None,
            "ready": False,
            "restarts": 0,
            "restart_started_at": None,
            "recoveries": 0,
            "deleted_at": None,
            "marks": [],
            # Placement decisions stamped by the provider (job, domain,
            # feature vector; see policy/features.py): the flight
            # recorder's contribution to the learned-policy corpus.
            "placements": [],
        }
        self.records[uid] = record
        self._by_key[record["key"]] = uid
        self._mark(record, now, "Created", "jobset created")
        if not queued:
            # Unqueued gangs admit at creation: the admission SLO is ~0 by
            # construction and the phase mark keeps timelines uniform.
            self._admit_locked(record, now, "admitted at creation (no queue)")
        while len(self.records) > MAX_RECORDS:
            evicted_uid, evicted = self.records.popitem(last=False)
            # Only drop the name-index entry if it still points at the
            # evicted record: a recreated JobSet under the same name owns
            # the key now, and evicting its predecessor must not blind
            # record_for() to the live gang.
            if self._by_key.get(evicted["key"]) == evicted_uid:
                self._by_key.pop(evicted["key"], None)

    def on_admitted(self, uid: str, now: Optional[float] = None) -> None:
        record = self.records.get(uid)
        if record is None:
            return
        if now is None:
            now = self.clock.now()
        if record["admitted_at"] is None:
            self._admit_locked(record, now, "gang admitted by queue")
        else:
            # Re-admission after preemption/voluntary requeue: a mark, not
            # a second time-to-admission sample.
            self._mark(record, now, "Readmitted", "gang re-admitted")

    def _admit_locked(self, record: dict, now: float, detail: str) -> None:
        from ..core import metrics

        record["admitted_at"] = now
        metrics.slo_time_to_admission_seconds.observe(
            max(0.0, now - record["created_at"])
        )
        self._mark(record, now, "Admitted", detail)

    def on_restart(self, uid: str, now: Optional[float] = None) -> None:
        record = self.records.get(uid)
        if record is None:
            return
        if now is None:
            now = self.clock.now()
        record["restarts"] += 1
        record["ready"] = False
        if record["restart_started_at"] is None:
            # Overlapping restarts before recovery extend ONE outage
            # window, measured from the first unrecovered restart.
            record["restart_started_at"] = now
        self._mark(
            record, now, "RestartStarted",
            f"gang restart {record['restarts']}",
        )

    def on_status(self, js, statuses, now: Optional[float] = None) -> None:
        """One call per reconcile status pass: detect the all-active
        (placement done) and all-ready transitions."""
        record = self.records.get(js.metadata.uid)
        if record is None:
            return
        replicas = {
            r.name: int(r.replicas) for r in js.spec.replicated_jobs
        }
        total = sum(replicas.values())
        if total == 0:
            return
        by_name = {s.name: s for s in statuses}
        if len(by_name) < len(replicas):
            return
        if now is None:
            now = self.clock.now()
        from ..core import metrics

        all_active = all(
            by_name[name].active >= n for name, n in replicas.items()
        )
        if all_active and record["scheduled_at"] is None:
            record["scheduled_at"] = now
            self._mark(
                record, now, "Scheduled",
                "all replicated jobs have active (placed) pods",
            )
        all_ready = all(
            by_name[name].ready >= n for name, n in replicas.items()
        )
        if all_ready and not record["ready"]:
            record["ready"] = True
            if record["restart_started_at"] is not None:
                outage = max(0.0, now - record["restart_started_at"])
                metrics.slo_restart_recovery_seconds.observe(outage)
                record["restart_started_at"] = None
                record["recoveries"] += 1
                self._mark(
                    record, now, "Recovered",
                    f"gang ready again {outage:.3f}s after restart",
                )
            if record["first_ready_at"] is None:
                record["first_ready_at"] = now
                metrics.slo_time_to_ready_seconds.observe(
                    max(0.0, now - record["created_at"])
                )
                self._mark(
                    record, now, "Ready", "every replica ready (gang up)"
                )
        elif not all_ready:
            record["ready"] = False

    def on_placed(
        self,
        uid: str,
        job: str,
        domain: str,
        features: list[float],
        source: str = "solver",
        now: Optional[float] = None,
    ) -> None:
        """One placement decision for one child job: the domain the
        provider chose and the candidate feature vector at decision time
        (``policy/features.py`` schema; the ``hist_*`` columns are zero by
        contract). Exported through the timeline into debug bundles, where
        ``policy/dataset.py`` joins decisions with outcomes into training
        examples."""
        record = self.records.get(uid)
        if record is None:
            return
        if now is None:
            now = self.clock.now()
        placements = record.setdefault("placements", [])
        placements.append({
            "time": now,
            "job": job,
            "domain": domain,
            "source": source,
            "restarts": record["restarts"],
            "features": [round(float(x), 6) for x in features],
        })
        if len(placements) > MAX_PLACEMENTS:
            del placements[: len(placements) - MAX_PLACEMENTS]

    def on_deleted(self, uid: str) -> None:
        """Mark the record deleted but KEEP it (until ring eviction): the
        postmortem use case is describing a JobSet precisely after it
        failed and was deleted. A recreation under the same name opens a
        fresh record that takes over the name index."""
        record = self.records.get(uid)
        if record is None:
            return
        now = self.clock.now()
        record["deleted_at"] = now
        self._mark(record, now, "Deleted", "jobset deleted")

    # Back-compat alias (the pre-review hook name).
    forget = on_deleted

    # -- read side ---------------------------------------------------------

    def record_for(self, namespace: str, name: str) -> Optional[dict]:
        uid = self._by_key.get((namespace, name))
        return self.records.get(uid) if uid is not None else None

    @staticmethod
    def _mark(record: dict, now: float, phase: str, detail: str) -> None:
        marks = record["marks"]
        marks.append({"time": now, "phase": phase, "detail": detail})
        if len(marks) > MAX_MARKS:
            del marks[: len(marks) - MAX_MARKS]


# ---------------------------------------------------------------------------
# /debug/slo summary
# ---------------------------------------------------------------------------


def _finite(value: float) -> Optional[float]:
    """nan (empty histogram) and inf (overflow bucket) are not JSON."""
    return round(value, 6) if math.isfinite(value) else None


def _histogram_summary(h) -> dict:
    mean = h.sum / h.n if h.n else None
    return {
        "count": h.n,
        "p50": _finite(h.percentile(0.50)),
        "p90": _finite(h.percentile(0.90)),
        "p99": _finite(h.percentile(0.99)),
        "mean": round(mean, 6) if mean is not None else None,
    }


def summary() -> dict:
    """The `/debug/slo` payload: percentile summaries of the three SLO
    histograms plus the solver-fallback ratio (local fallbacks over all
    placement solve outcomes — the fraction of placements that did NOT get
    the optimizing path)."""
    from ..core import metrics

    fallbacks = metrics.solver_fallbacks_total.total()
    solves = metrics.solver_solve_time_seconds.n
    attempts = fallbacks + solves
    return {
        "timeToAdmissionSeconds": _histogram_summary(
            metrics.slo_time_to_admission_seconds
        ),
        "timeToReadySeconds": _histogram_summary(
            metrics.slo_time_to_ready_seconds
        ),
        "restartRecoverySeconds": _histogram_summary(
            metrics.slo_restart_recovery_seconds
        ),
        "solverFallbackRatio": (
            round(fallbacks / attempts, 4) if attempts else 0.0
        ),
        "solverFallbacks": fallbacks,
        "solverSolves": solves,
    }
