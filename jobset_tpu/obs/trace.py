"""In-process span tracer with W3C traceparent propagation.

Design constraints, in order:

1. **Hot-path cheap.** Spans are started/ended inside timed reconcile
   passes (the very latencies they attribute), so the per-span cost is a
   ``perf_counter`` pair, a couple of dict writes, and one contextvar
   set/reset — no locks until a whole trace finishes.
2. **Bounded memory.** Finished traces land in a ring buffer
   (``maxlen`` traces); open traces that never finish (a crashed request)
   are capped too, evicted FIFO. A long-running controller's trace memory
   is flat regardless of churn.
3. **Cross-process by header only.** Propagation is the W3C
   ``traceparent`` header (``00-<32hex trace>-<16hex span>-<2hex flags>``),
   injected by the HTTP client and extracted by the server — the exact
   contract real OpenTelemetry stacks interoperate on, so swapping this
   tracer for an OTLP exporter later changes no call sites.

Context propagation uses ``contextvars``: each server handler thread and
the background pump thread get independent active-span state for free,
while nested ``with span(...)`` blocks inside one request chain correctly.
"""

from __future__ import annotations

import contextvars
import random
import threading
import time
from collections import OrderedDict, deque
from dataclasses import dataclass
from typing import Optional

_TRACEPARENT_VERSION = "00"
_SAMPLED_FLAGS = "01"


@dataclass(frozen=True)
class SpanContext:
    """Immutable identity of a span: what crosses process boundaries and
    what children parent onto. ``trace_id`` is 32 lowercase hex chars,
    ``span_id`` 16 — W3C trace-context sizes."""

    trace_id: str
    span_id: str

    def to_traceparent(self) -> str:
        return (
            f"{_TRACEPARENT_VERSION}-{self.trace_id}-{self.span_id}"
            f"-{_SAMPLED_FLAGS}"
        )


def extract_traceparent(header: Optional[str]) -> Optional[SpanContext]:
    """Parse a W3C traceparent header into a SpanContext, or None when the
    header is absent/malformed (a bad header must never fail a request —
    the trace just starts fresh server-side)."""
    if not header:
        return None
    parts = header.strip().split("-")
    # Version 00 is exactly 4 fields (W3C trace-context §traceparent);
    # extra fields or a non-2-hex flags byte mean a malformed header and
    # the trace restarts here.
    if len(parts) != 4 or parts[0] != _TRACEPARENT_VERSION:
        return None
    trace_id, span_id, flags = parts[1].lower(), parts[2].lower(), parts[3]
    if len(trace_id) != 32 or len(span_id) != 16 or len(flags) != 2:
        return None
    try:
        int(trace_id, 16), int(span_id, 16), int(flags, 16)
    except ValueError:
        return None
    if trace_id == "0" * 32 or span_id == "0" * 16:
        return None  # all-zero ids are invalid per the spec
    return SpanContext(trace_id=trace_id, span_id=span_id)


class Span:
    """One timed operation. Mutable while open; a finished span is frozen
    into a plain dict inside its trace record (``to_dict``)."""

    __slots__ = (
        "name",
        "context",
        "parent_id",
        "attributes",
        "start_wall",
        "_start_perf",
        "duration_s",
        "status",
        "_tracer",
        "_token",
        "_is_local_root",
    )

    def __init__(
        self,
        tracer: "Tracer",
        name: str,
        context: SpanContext,
        parent_id: Optional[str],
        attributes: Optional[dict] = None,
    ):
        self.name = name
        self.context = context
        self.parent_id = parent_id
        self.attributes: dict = dict(attributes or {})
        # jslint: disable=DET001 span wall stamps are viewer display metadata; durations use perf_counter, timelines correlate by trace id — never byte-compared
        self.start_wall = time.time()
        self._start_perf = time.perf_counter()
        self.duration_s: Optional[float] = None
        self.status = "ok"
        self._tracer = tracer
        self._token: Optional[contextvars.Token] = None
        self._is_local_root = False

    # -- enrichment -------------------------------------------------------

    def set_attribute(self, key: str, value) -> "Span":
        self.attributes[key] = value
        return self

    def record_error(self, exc: BaseException) -> None:
        self.status = "error"
        self.attributes["error"] = f"{type(exc).__name__}: {exc}"[:200]

    # -- lifecycle --------------------------------------------------------

    def end(self) -> None:
        if self.duration_s is not None:
            return  # idempotent: double-end keeps the first duration
        self.duration_s = time.perf_counter() - self._start_perf
        self._tracer._on_span_end(self)

    def __enter__(self) -> "Span":
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        if exc is not None:
            self.record_error(exc)
        if self._token is not None:
            _current_span.reset(self._token)
            self._token = None
        self.end()
        return False

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "trace_id": self.context.trace_id,
            "span_id": self.context.span_id,
            "parent_span_id": self.parent_id,
            "start_unix_s": round(self.start_wall, 6),
            "duration_ms": round((self.duration_s or 0.0) * 1000.0, 4),
            "status": self.status,
            "attributes": self.attributes,
        }


_current_span: contextvars.ContextVar[Optional[Span]] = contextvars.ContextVar(
    "jobset_tpu_current_span", default=None
)


class Tracer:
    """Span factory + bounded store of finished traces.

    A *trace record* accumulates the finished spans of one trace id. The
    record moves to the finished ring when its **root** span (the one with
    no parent inside this process) ends; spans that finish later — e.g. a
    solver readback fetched ticks after the reconcile that dispatched it —
    are appended to the record wherever it lives, so async tails still
    attribute to the right trace.
    """

    def __init__(self, max_traces: int = 256, max_spans_per_trace: int = 512):
        self.max_traces = max_traces
        self.max_spans_per_trace = max_spans_per_trace
        self._lock = threading.Lock()
        # trace_id -> record; record: {"trace_id", "spans": [dict], "roots": int}
        self._open: "OrderedDict[str, dict]" = OrderedDict()
        self._finished: "deque[dict]" = deque(maxlen=max_traces)
        self._by_id: dict[str, dict] = {}  # finished records still in the ring
        self.dropped_spans = 0
        # Optional complete duration log (enable_duration_log): every ended
        # span's duration by name, independent of ring eviction — the bench
        # needs whole-run phase percentiles, and a 512-pod recovery roots
        # far more than max_traces traces. Unbounded while enabled, so not
        # for long-running servers (the Histogram.enable_raw pattern).
        self._duration_log: Optional[dict[str, list[float]]] = None

    # -- id generation ----------------------------------------------------

    # Mersenne-Twister ids, not os.urandom: span creation sits inside timed
    # reconcile passes (some reconciles are ~30 us) and getrandbits avoids a
    # syscall per id. Uniqueness, not unpredictability, is the requirement.
    @staticmethod
    def _new_trace_id() -> str:
        # jslint: disable=DET002 deliberately the process-global stream: seeded soaks random.seed() it so trace ids reproduce (test_timeline byte-identical runs)
        return f"{random.getrandbits(128):032x}"

    @staticmethod
    def _new_span_id() -> str:
        # jslint: disable=DET002 deliberately the process-global stream: seeded soaks random.seed() it so trace ids reproduce (test_timeline byte-identical runs)
        return f"{random.getrandbits(64):016x}"

    # -- span lifecycle ---------------------------------------------------

    def start_span(
        self,
        name: str,
        attributes: Optional[dict] = None,
        parent: Optional[SpanContext] = None,
        activate: bool = True,
    ) -> Span:
        """Open a span. Parent resolution: explicit ``parent`` (e.g. an
        extracted traceparent) wins, else the context-active span, else a
        fresh root trace. ``activate=False`` opens a span without making it
        the context parent (for spans whose children intentionally attach
        elsewhere, like a fire-and-forget dispatch)."""
        is_root = False
        if parent is None:
            active = _current_span.get()
            if active is not None:
                parent = active.context
        if parent is None:
            trace_id = self._new_trace_id()
            parent_id = None
            is_root = True
        else:
            trace_id = parent.trace_id
            parent_id = parent.span_id
        span = Span(
            self,
            name,
            SpanContext(trace_id=trace_id, span_id=self._new_span_id()),
            parent_id,
            attributes,
        )
        with self._lock:
            record = self._record_for_locked(trace_id)
            if record is None:
                # New local trace — either a genuine root or the first span
                # under a remote parent (extracted traceparent): either way
                # this span is the LOCAL root whose end finishes the record.
                record = self._open_record_locked(trace_id)
                is_root = True
            if is_root and trace_id in self._open:
                record["roots"] += 1
        if is_root:
            span._is_local_root = True  # type: ignore[attr-defined]
        if activate:
            span._token = _current_span.set(span)
        return span

    def record_span(
        self,
        name: str,
        duration_s: float,
        attributes: Optional[dict] = None,
        parent: Optional[SpanContext] = None,
    ) -> Span:
        """Synthesize an already-finished span from externally-measured
        timestamps — e.g. the solver's device-side solve loop, whose wall
        time is known only at readback. Parents like start_span (explicit
        parent, else active span, else fresh root)."""
        s = self.start_span(name, attributes=attributes, parent=parent,
                            activate=False)
        s.start_wall -= duration_s  # it ENDED now; it started duration ago
        s.duration_s = max(0.0, duration_s)
        self._on_span_end(s)
        return s

    def _open_record_locked(self, trace_id: str) -> dict:
        record = {"trace_id": trace_id, "spans": [], "roots": 0}
        self._open[trace_id] = record
        while len(self._open) > self.max_traces:
            self._open.popitem(last=False)  # FIFO-evict never-finished traces
        return record

    def _record_for_locked(self, trace_id: str) -> Optional[dict]:
        record = self._open.get(trace_id)
        if record is None:
            record = self._by_id.get(trace_id)
        return record

    def enable_duration_log(self) -> None:
        """Record EVERY ended span's duration by name (bench use —
        unbounded memory, so not for long-running servers). Survives
        reset(); contents clear with it."""
        with self._lock:
            self._duration_log = {}

    def _on_span_end(self, span: Span) -> None:
        trace_id = span.context.trace_id
        with self._lock:
            if self._duration_log is not None:
                self._duration_log.setdefault(span.name, []).append(
                    span.duration_s or 0.0
                )
            record = self._record_for_locked(trace_id)
            if record is None:
                # Trace evicted before this late span finished: count, drop.
                self.dropped_spans += 1
                return
            if len(record["spans"]) < self.max_spans_per_trace:
                record["spans"].append(span.to_dict())
            else:
                self.dropped_spans += 1
            if getattr(span, "_is_local_root", False) and trace_id in self._open:
                record["roots"] -= 1
                if record["roots"] <= 0:
                    self._open.pop(trace_id, None)
                    self._finish_record_locked(record)

    def _finish_record_locked(self, record: dict) -> None:
        if len(self._finished) == self._finished.maxlen:
            evicted = self._finished[0]
            self._by_id.pop(evicted["trace_id"], None)
        self._finished.append(record)
        self._by_id[record["trace_id"]] = record

    # -- read side --------------------------------------------------------

    def finished_traces(self, limit: int = 0) -> list[dict]:
        """Most-recent-last snapshot of finished traces (deep enough copies
        that callers can serialize without racing span appends)."""
        with self._lock:
            records = list(self._finished)
            if limit:
                records = records[-limit:]
            return [
                {
                    "trace_id": r["trace_id"],
                    "spans": list(r["spans"]),
                }
                for r in records
            ]

    def span_durations_s(self, include_open: bool = True) -> dict[str, list[float]]:
        """All recorded span durations grouped by span name, in seconds —
        the bench's per-phase percentile source. With the duration log
        enabled this covers EVERY ended span of the run; otherwise it falls
        back to the bounded ring (most recent ``max_traces`` traces only).
        ``include_open`` also reads spans already finished inside
        still-open traces (ring fallback path)."""
        with self._lock:
            if self._duration_log is not None:
                return {k: list(v) for k, v in self._duration_log.items()}
            out: dict[str, list[float]] = {}
            records = list(self._finished)
            if include_open:
                records += list(self._open.values())
            for record in records:
                for s in record["spans"]:
                    out.setdefault(s["name"], []).append(
                        s["duration_ms"] / 1000.0
                    )
            return out

    def reset(self) -> None:
        """Test/bench helper: drop all trace state (the duration log stays
        enabled if it was, but empties)."""
        with self._lock:
            self._open.clear()
            self._finished.clear()
            self._by_id.clear()
            self.dropped_spans = 0
            if self._duration_log is not None:
                self._duration_log = {}


# Process-global tracer (one per process, like the metrics registry).
TRACER = Tracer()


def duration_log_enabled() -> bool:
    """True while the global tracer's duration log is recording (bench
    runs). The per-tick phase attribution gates its `tick.*` span
    synthesis on this: each phase span roots a fresh trace, and an
    always-on feed would flood the finished-trace ring in live servers
    — the metrics histogram (`jobset_tick_phase_seconds`) is the
    always-on surface instead."""
    with TRACER._lock:
        return TRACER._duration_log is not None


def span(
    name: str,
    attributes: Optional[dict] = None,
    parent: Optional[SpanContext] = None,
    activate: bool = True,
) -> Span:
    """`with span("reconcile", {...}):` — the one-call hot-path API."""
    return TRACER.start_span(
        name, attributes=attributes, parent=parent, activate=activate
    )


def current_span() -> Optional[Span]:
    return _current_span.get()


def current_trace_id() -> Optional[str]:
    active = _current_span.get()
    return active.context.trace_id if active is not None else None


def current_traceparent() -> Optional[str]:
    """The header value to inject on outbound requests, or None when no
    span is active (callers simply omit the header)."""
    active = _current_span.get()
    return active.context.to_traceparent() if active is not None else None
