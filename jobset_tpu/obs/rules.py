"""PromQL-lite rule engine for the telemetry plane.

A small, deterministic expression language evaluated against the
embedded TSDB (``obs/tsdb.py``) — enough PromQL to express the rules a
control plane actually gates on, nothing more:

* selectors — ``jobset_flow_rejected_total{level="workload-low"}`` and
  range selectors ``...[60s]``
* ``rate(v[w])`` / ``increase(v[w])`` — counter-reset corrected; a
  series born inside the window is credited from 0 (see
  ``TimeSeriesStore.window``)
* ``histogram_quantile(q, expr)`` over ``_bucket`` series
* ``slo_burn_rate(family, objective_s, target, window)`` — the SRE-
  workbook burn rate: (bad fraction over window) / (1 - target), where
  "bad" is observations of histogram ``family`` above ``objective_s``
  (snapped to the enclosing bucket bound)
* aggregation — ``sum|max|avg|min [by (l1, l2)] (expr)``
* scalar comparison filters — ``expr > 2`` keeps vector elements whose
  value passes (Prometheus semantics: an empty result means "nothing
  firing")
* ``and`` — vector intersection on label sets (multi-window burn rules)

Declarative rule files (YAML or JSON, the Prometheus shape)::

    groups:
      - rules:
          - record: jobset:flow_rejected:rate1m
            expr: sum(rate(jobset_flow_rejected_total[60s]))
          - alert: JobSetFlowShedRateHigh
            expr: sum(rate(jobset_flow_rejected_total[60s])) > 1
            for: 0s
            labels: {severity: page}
            annotations: {summary: "..."}

Everything evaluates at an explicit ``now`` with pure float arithmetic
over decoded samples — two seeded runs produce byte-identical results.
"""

from __future__ import annotations

import json
import re

# Staleness lookback for instant selectors (Prometheus' 5 m default).
DEFAULT_LOOKBACK_S = 300.0

_AGG_OPS = ("sum", "max", "avg", "min")
_CMP_OPS = (">=", "<=", "==", "!=", ">", "<")

_TOKEN_RE = re.compile(
    r"\s*(?:"
    r"(?P<duration>\d+(?:\.\d+)?(?:ms|s|m|h|d))"
    r"|(?P<number>\d+(?:\.\d+)?(?:[eE][+-]?\d+)?)"
    r"|(?P<name>[A-Za-z_:][A-Za-z0-9_:]*)"
    r"|(?P<string>\"[^\"]*\"|'[^']*')"
    r"|(?P<op>>=|<=|==|!=|[><(){}\[\],=])"
    r")"
)

_DURATION_UNITS = {"ms": 0.001, "s": 1.0, "m": 60.0, "h": 3600.0,
                   "d": 86400.0}


class RuleError(ValueError):
    """Malformed expression or rule file."""


def parse_duration(text: str) -> float:
    m = re.fullmatch(r"(\d+(?:\.\d+)?)(ms|s|m|h|d)?", str(text).strip())
    if not m:
        raise RuleError(f"bad duration {text!r}")
    return float(m.group(1)) * _DURATION_UNITS.get(m.group(2) or "s", 1.0)


def _tokenize(text: str) -> list[tuple[str, str]]:
    tokens, pos = [], 0
    while pos < len(text):
        m = _TOKEN_RE.match(text, pos)
        if m is None or m.end() == pos:
            if text[pos:].strip():
                raise RuleError(
                    f"unexpected character {text[pos:].strip()[0]!r} in "
                    f"expression {text!r}"
                )
            break
        pos = m.end()
        for kind in ("duration", "number", "name", "string", "op"):
            val = m.group(kind)
            if val is not None:
                tokens.append((kind, val))
                break
    return tokens


class _Parser:
    def __init__(self, text: str):
        self.text = text
        self.tokens = _tokenize(text)
        self.pos = 0

    def peek(self):
        return self.tokens[self.pos] if self.pos < len(self.tokens) else None

    def next(self):
        tok = self.peek()
        if tok is None:
            raise RuleError(f"unexpected end of expression {self.text!r}")
        self.pos += 1
        return tok

    def expect(self, value: str):
        tok = self.next()
        if tok[1] != value:
            raise RuleError(
                f"expected {value!r}, got {tok[1]!r} in {self.text!r}"
            )
        return tok

    # expr := cmp ('and' cmp)*
    def parse(self):
        node = self._cmp()
        while True:
            tok = self.peek()
            if tok and tok[0] == "name" and tok[1] == "and":
                self.next()
                node = ("and", node, self._cmp())
            else:
                break
        return node

    def _cmp(self):
        node = self._primary()
        tok = self.peek()
        if tok and tok[0] == "op" and tok[1] in _CMP_OPS:
            op = self.next()[1]
            rhs = self.next()
            if rhs[0] not in ("number", "duration"):
                raise RuleError(
                    f"comparison needs a scalar rhs in {self.text!r}"
                )
            node = ("cmp", op, node, float(rhs[1].rstrip("smhd")
                                           if rhs[0] == "duration"
                                           else rhs[1]))
        return node

    def _primary(self):
        tok = self.next()
        if tok[0] == "number":
            return ("scalar", float(tok[1]))
        if tok[0] == "op" and tok[1] == "(":
            node = self.parse()
            self.expect(")")
            return node
        if tok[0] != "name":
            raise RuleError(f"unexpected {tok[1]!r} in {self.text!r}")
        name = tok[1]
        if name in _AGG_OPS:
            by = ()
            nxt = self.peek()
            if nxt and nxt[0] == "name" and nxt[1] == "by":
                self.next()
                self.expect("(")
                labels = []
                while True:
                    labels.append(self.next()[1])
                    if self.peek() and self.peek()[1] == ",":
                        self.next()
                        continue
                    break
                self.expect(")")
                by = tuple(labels)
            self.expect("(")
            inner = self.parse()
            self.expect(")")
            return ("agg", name, by, inner)
        if name in ("rate", "increase"):
            self.expect("(")
            inner = self._primary()
            self.expect(")")
            if inner[0] != "range":
                raise RuleError(
                    f"{name}() needs a range selector like v[60s] in "
                    f"{self.text!r}"
                )
            return (name, inner)
        if name == "histogram_quantile":
            self.expect("(")
            q = self.next()
            if q[0] != "number":
                raise RuleError("histogram_quantile needs a scalar q")
            self.expect(",")
            inner = self.parse()
            self.expect(")")
            return ("quantile", float(q[1]), inner)
        if name == "slo_burn_rate":
            self.expect("(")
            family = self.next()
            if family[0] != "name":
                raise RuleError("slo_burn_rate needs a histogram family")
            self.expect(",")
            objective = float(self.next()[1])
            self.expect(",")
            target = float(self.next()[1])
            self.expect(",")
            window_tok = self.next()
            window = parse_duration(window_tok[1])
            self.expect(")")
            return ("burn", family[1], objective, target, window)
        # plain selector: name{matchers}[window]
        matchers: dict[str, str] = {}
        nxt = self.peek()
        if nxt and nxt[1] == "{":
            self.next()
            while self.peek() and self.peek()[1] != "}":
                label = self.next()[1]
                self.expect("=")
                value = self.next()
                if value[0] != "string":
                    raise RuleError(
                        f"matcher value must be quoted in {self.text!r}"
                    )
                matchers[label] = value[1][1:-1]
                if self.peek() and self.peek()[1] == ",":
                    self.next()
            self.expect("}")
        nxt = self.peek()
        if nxt and nxt[1] == "[":
            self.next()
            window_tok = self.next()
            window = parse_duration(window_tok[1])
            self.expect("]")
            return ("range", name, matchers, window)
        return ("selector", name, matchers)


def parse(text: str):
    """Parse one expression into an AST (nested tuples)."""
    parser = _Parser(text)
    node = parser.parse()
    if parser.peek() is not None:
        raise RuleError(
            f"trailing tokens after expression: {parser.peek()[1]!r} in "
            f"{text!r}"
        )
    return node


# ---------------------------------------------------------------------------
# Evaluation
# ---------------------------------------------------------------------------


def _counter_delta(samples: list, born_in_window: bool) -> float:
    """Counter increase over the window with reset correction; a series
    born inside the window is credited from 0."""
    delta = 0.0
    prev = samples[0][1]
    for _, v in samples[1:]:
        delta += (v - prev) if v >= prev else v
        prev = v
    if born_in_window:
        delta += samples[0][1]
    return delta


def _match_key(labels: dict) -> tuple:
    return tuple(sorted(labels.items()))


def evaluate(node, tsdb, now: float,
             lookback: float = DEFAULT_LOOKBACK_S) -> list:
    """Evaluate an AST at instant ``now`` -> instant vector
    ``[(labels_dict, value), ...]`` in deterministic (sorted) order."""
    kind = node[0]
    if kind == "scalar":
        return [({}, node[1])]
    if kind == "selector":
        _, name, matchers = node
        return tsdb.instant(name, matchers, now, lookback)
    if kind == "range":
        raise RuleError("range selector needs rate()/increase() around it")
    if kind in ("rate", "increase"):
        _, name, matchers, window = node[1]
        out = []
        for labels, samples, born in tsdb.window(name, matchers, now,
                                                 window):
            delta = _counter_delta(samples, born)
            out.append((labels, delta / window if kind == "rate"
                        else delta))
        return out
    if kind == "agg":
        _, op, by, inner = node
        vec = evaluate(inner, tsdb, now, lookback)
        groups: dict[tuple, list[float]] = {}
        for labels, value in vec:
            key = tuple((l, labels.get(l, "")) for l in by)
            groups.setdefault(key, []).append(value)
        out = []
        for key in sorted(groups):
            vals = groups[key]
            if op == "sum":
                value = sum(vals)
            elif op == "max":
                value = max(vals)
            elif op == "min":
                value = min(vals)
            else:
                value = sum(vals) / len(vals)
            out.append((dict(key), value))
        return out
    if kind == "cmp":
        _, op, inner, threshold = node
        vec = evaluate(inner, tsdb, now, lookback)
        keep = {
            ">": lambda v: v > threshold,
            "<": lambda v: v < threshold,
            ">=": lambda v: v >= threshold,
            "<=": lambda v: v <= threshold,
            "==": lambda v: v == threshold,
            "!=": lambda v: v != threshold,
        }[op]
        return [(labels, v) for labels, v in vec if keep(v)]
    if kind == "and":
        _, left, right = node
        lvec = evaluate(left, tsdb, now, lookback)
        rkeys = {_match_key(labels)
                 for labels, _ in evaluate(right, tsdb, now, lookback)}
        return [(labels, v) for labels, v in lvec
                if _match_key(labels) in rkeys]
    if kind == "quantile":
        _, q, inner = node
        vec = evaluate(inner, tsdb, now, lookback)
        return _histogram_quantile(q, vec)
    if kind == "burn":
        _, family, objective, target, window = node
        return _slo_burn_rate(tsdb, now, family, objective, target, window)
    raise RuleError(f"unknown node kind {kind!r}")


def _histogram_quantile(q: float, vec: list) -> list:
    """phi-quantile over ``_bucket`` elements (le label), grouped by the
    remaining labels — the Prometheus estimator: upper bound of the
    first bucket whose cumulative count crosses q*total."""
    groups: dict[tuple, list[tuple[float, float]]] = {}
    for labels, value in vec:
        le = labels.get("le")
        if le is None:
            continue
        bound = float("inf") if le == "+Inf" else float(le)
        rest = tuple(sorted(
            (k, v) for k, v in labels.items() if k != "le"
        ))
        groups.setdefault(rest, []).append((bound, value))
    out = []
    for rest in sorted(groups):
        buckets = sorted(groups[rest])
        total = buckets[-1][1] if buckets else 0.0
        if total <= 0:
            continue
        target = q * total
        value = buckets[-1][0]
        for bound, cumulative in buckets:
            if cumulative >= target:
                value = bound
                break
        out.append((dict(rest), value))
    return out


def _slo_burn_rate(tsdb, now: float, family: str, objective: float,
                   target: float, window: float) -> list:
    """Burn rate of histogram ``family`` against ``objective`` seconds at
    ``target`` availability over ``window``: bad-fraction / error-budget.
    The objective snaps to the smallest bucket bound >= objective (bucket
    ladders quantize; docs/observability.md)."""
    buckets = tsdb.window(f"{family}_bucket", {}, now, window)
    counts = tsdb.window(f"{family}_count", {}, now, window)
    # Group buckets by non-le labels, picking the snapped objective bound.
    good: dict[tuple, float] = {}
    for labels, samples, born in buckets:
        le = labels.get("le", "")
        bound = float("inf") if le == "+Inf" else float(le)
        if bound < objective:
            continue
        rest = tuple(sorted(
            (k, v) for k, v in labels.items() if k != "le"
        ))
        prev = good.get(rest)
        if prev is None or bound < prev[0]:
            good[rest] = (bound, _counter_delta(samples, born))
    out = []
    budget = max(1e-9, 1.0 - target)
    for labels, samples, born in sorted(
        counts, key=lambda item: _match_key(item[0])
    ):
        rest = _match_key(labels)
        total = _counter_delta(samples, born)
        if total <= 0:
            out.append((labels, 0.0))
            continue
        good_delta = good.get(rest, (None, 0.0))[1]
        bad_ratio = max(0.0, (total - good_delta) / total)
        out.append((labels, bad_ratio / budget))
    return out


# ---------------------------------------------------------------------------
# Declarative rules
# ---------------------------------------------------------------------------


class RecordingRule:
    def __init__(self, name: str, expr: str):
        self.name = name
        self.expr = expr
        self.ast = parse(expr)

    def to_dict(self) -> dict:
        return {"record": self.name, "expr": self.expr}


class AlertRule:
    def __init__(self, name: str, expr: str, for_s: float = 0.0,
                 labels: dict | None = None,
                 annotations: dict | None = None):
        self.name = name
        self.expr = expr
        self.ast = parse(expr)
        self.for_s = float(for_s)
        self.labels = dict(labels or {})
        self.annotations = dict(annotations or {})

    def to_dict(self) -> dict:
        return {
            "alert": self.name,
            "expr": self.expr,
            "for": self.for_s,
            "labels": dict(self.labels),
            "annotations": dict(self.annotations),
        }


def load_rules_dict(doc: dict) -> tuple[list[RecordingRule],
                                        list[AlertRule]]:
    """Parse the Prometheus rule-file shape (``groups: [{rules: [...]}]``
    or a bare ``rules:`` list) into rule objects."""
    if not isinstance(doc, dict):
        raise RuleError("rule file must be a mapping")
    if "groups" in doc:
        entries = []
        for group in doc.get("groups") or []:
            entries.extend(group.get("rules") or [])
    else:
        entries = doc.get("rules") or []
    recording, alerts = [], []
    for entry in entries:
        if "record" in entry:
            recording.append(RecordingRule(entry["record"], entry["expr"]))
        elif "alert" in entry:
            alerts.append(AlertRule(
                entry["alert"], entry["expr"],
                for_s=parse_duration(entry.get("for", 0)),
                labels=entry.get("labels"),
                annotations=entry.get("annotations"),
            ))
        else:
            raise RuleError(
                f"rule entry needs 'record' or 'alert': {entry!r}"
            )
    return recording, alerts


def load_rules_file(path: str) -> tuple[list[RecordingRule],
                                        list[AlertRule]]:
    with open(path) as f:
        text = f.read()
    try:
        doc = json.loads(text)
    except ValueError:
        import yaml

        doc = yaml.safe_load(text)
    return load_rules_dict(doc)
