"""Observability plane: in-process tracing + structured logging.

The reference JobSet inherits controller-runtime's /metrics endpoint and
nothing else — a slow reconcile is unattributable to the placement solve,
gRPC hop, or apiserver write that caused it (the round-5 VERDICT's
evidence-integrity gap). This package closes that gap without external
dependencies:

* ``trace``   — an in-process span tracer (parent/child spans, attributes,
  a bounded ring buffer of finished traces) with W3C ``traceparent``
  propagation, so one trace covers client request -> apiserver handler ->
  reconcile pump -> placement provider -> solver phases.
* ``logging`` — a structured JSON log formatter that stamps every record
  with the active span's trace/span ids, so logs and traces join on ids.
* ``slo``     — per-JobSet lifecycle SLO tracking (time-to-admission,
  time-to-ready, restart-recovery histograms) measured on the cluster
  clock, summarized at ``GET /debug/slo``.
* ``timeline`` — the flight recorder: a per-JobSet assembler correlating
  phase marks, conditions, trace-id-stamped events, chaos injections and
  store commit points into one ordered record
  (``GET /debug/timeline/{ns}/{name}``, ``jobset-tpu describe``).
* ``bundle``  — one-command postmortem export (``jobset-tpu
  debug-bundle OUT.tgz``) and its loader.

Everything here is stdlib-only and import-light: the control plane's hot
paths call into it on every reconcile, so span start/end is a few dict
ops, one contextvar set/reset, and one short tracer-lock acquisition each
(uncontended in the single-threaded pump; ~100 ns) — no serialization,
I/O, or allocation beyond the span dict itself.
"""

from .trace import (
    SpanContext,
    Tracer,
    TRACER,
    current_span,
    current_traceparent,
    extract_traceparent,
    span,
)
from .logging import JsonLogFormatter, configure_json_logging, get_logger
from .slo import LifecycleTracker

__all__ = [
    "JsonLogFormatter",
    "LifecycleTracker",
    "SpanContext",
    "TRACER",
    "Tracer",
    "configure_json_logging",
    "current_span",
    "current_traceparent",
    "extract_traceparent",
    "get_logger",
    "span",
]
