"""Lock-contention profiler: acquire-wait timing on named locks.

The race harness (``testing/race.py``) already owns the only sanctioned
way to interpose on the tree's locks — swap an instance's Lock/RLock
attributes for wrappers before its threads start. This module reuses
that seam (:func:`testing.race.swap_lock_attrs`) for production
telemetry instead of test-time race detection: each instrumented lock
becomes a :class:`TimedLock` that times *contended* acquires into
``jobset_lock_wait_seconds{lock}`` (docs/metrics.md), which the
telemetry TSDB samples every tick and the default
``JobSetLockContentionHigh`` alert watches (docs/observability.md).

Measurement discipline:

* Only contended acquires are observed. The fast path is a single
  non-blocking ``acquire(False)`` — an uncontended lock costs one extra
  C call and produces no sample, so the histogram answers "how long do
  waiters wait", not "how often is the lock taken" (that would bury the
  signal under millions of zero rows and add a clock read per acquire).
* Waits are timed with ``time.perf_counter`` — latency measurement,
  never decision state, so the seeded planes stay DET001-green.
* Installation follows the race harness's rule: swap only before the
  owning object's threads run (``instrument()`` at construction/wiring
  time, e.g. ``controller --profile`` before ``serve()``).
"""

from __future__ import annotations

import threading
import time

from ..core import metrics
from ..testing.race import swap_lock_attrs


class TimedLock:
    """Lock/RLock wrapper that observes contended acquire-waits.

    Works for both lock types: the reentrant re-acquire of an RLock by
    its holder succeeds on the non-blocking fast path, so reentrancy
    never records a phantom wait. Presents the full lock surface
    (context manager, ``locked()``, ``_at_fork_reinit``) so it drops in
    anywhere the bare primitive lived."""

    __slots__ = ("_inner", "_name")

    def __init__(self, inner, name: str):
        self._inner = inner
        self._name = name

    def acquire(self, blocking: bool = True, timeout: float = -1):
        if self._inner.acquire(False):
            return True
        if not blocking:
            return False
        t0 = time.perf_counter()
        got = self._inner.acquire(True, timeout)
        metrics.lock_wait_seconds.observe(
            time.perf_counter() - t0, self._name
        )
        return got

    def release(self):
        self._inner.release()

    def locked(self):
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def _at_fork_reinit(self):  # pragma: no cover - forking servers only
        self._inner._at_fork_reinit()


class ContentionProfiler:
    """Registry of instrumented objects; ``instrument(obj, prefix)``
    swaps every bare Lock/RLock attribute for a :class:`TimedLock`
    named ``{prefix}.{attr}`` and remembers the original so
    ``uninstall()`` can restore it (test hygiene — live controllers
    keep the wrappers for the process lifetime)."""

    def __init__(self):
        self._installed: list[tuple[object, str, object]] = []  # guarded-by: _lock
        self._lock = threading.Lock()

    def instrument(self, obj, prefix: str) -> list[str]:
        """Returns the instrumented lock names (``prefix.attr``)."""
        swapped = swap_lock_attrs(
            obj, lambda name, value: TimedLock(value, f"{prefix}.{name}")
        )
        with self._lock:
            for name, original in swapped:
                self._installed.append((obj, name, original))
        return [f"{prefix}.{name}" for name, _ in swapped]

    def uninstall(self) -> None:
        with self._lock:
            installed, self._installed = self._installed, []
        for obj, name, original in reversed(installed):
            object.__setattr__(obj, name, original)

    def names(self) -> list[str]:
        """Instrumented lock names as exported (sorted, for /debug)."""
        with self._lock:
            return sorted(
                getattr(obj, name)._name
                for obj, name, _ in self._installed
                if isinstance(getattr(obj, name, None), TimedLock)
            )

    def snapshot(self) -> dict[str, dict]:
        return snapshot()


def snapshot() -> dict[str, dict]:
    """Per-lock wait stats for /debug/profile: contended-acquire count,
    total wait, and p99 from the histogram ladder. Reads the process-
    global ``jobset_lock_wait_seconds`` family, so it covers every
    installed TimedLock regardless of which profiler installed it."""
    out: dict[str, dict] = {}
    for labels, hist in metrics.lock_wait_seconds.children():
        with hist._lock:
            n, total = hist.n, hist.sum
        out[labels[0]] = {
            "waits": n,
            "wait_seconds_total": total,
            "p99_s": metrics.lock_wait_seconds.percentile(
                0.99, *labels
            ) if n else 0.0,
        }
    return out
