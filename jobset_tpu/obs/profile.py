"""Continuous profiling plane: sampled stacks + JIT/kernel telemetry.

Two halves live here (docs/observability.md "Continuous profiling"):

* :class:`StackProfiler` — a sampling wall-clock profiler. A daemon
  thread walks ``sys._current_frames()`` at a configurable rate
  (``controller --profile [--profile-hz]``) and folds each thread's
  stack into a bounded aggregation trie rooted at the thread's *role*
  (pump / handler / sampler / replication / drain / main), so the
  flamegraph reads as "where does each control-plane thread spend its
  time" rather than one undifferentiated blob. ``GET /debug/profile``
  serves the trie as folded-stack lines (flamegraph.pl input) and a
  top-N self/total table, plus a ring of per-interval aggregates so a
  transient stall is still attributable after it passes.

  Telemetry-plane discipline applies: the clock is injectable and
  ``sample(now=, frames=)`` is a synchronous path that takes synthetic
  stacks, so tests exercise fold/ring/bound logic deterministically —
  no wall reads (DET001), no sleeps, no real threads required.

* JIT/kernel observability — the runtime teeth for JIT002/JIT004. The
  compile-once bucket factories (solver, queue scorer, columnar
  aggregates, policy MLP) wrap their freshly-jitted kernels in
  :func:`timed_compile` (first invocation per specialization = the
  trace+lower+compile cost, observed into ``jobset_jit_compile_seconds``
  and counted in ``jobset_jit_compiles_total``) and register their
  ``lru_cache`` handles with :data:`KERNEL_CACHES` so the
  ``jobset_jit_cache_{hits,misses}`` gauges read ``cache_info()`` at
  collect time. :func:`note_transfer` accounts host<->device bytes at
  instrumented call sites (``jobset_jit_transfer_bytes_total``).

Overhead contract: sampling at the default 67 Hz must cost <=3% of a
core (``bench.py --profile`` banks the measured duty cycle); a sampler
pass that overruns its period bumps ``jobset_profile_overruns_total``.
"""

from __future__ import annotations

import functools
import sys
import threading
import time
from collections import deque

from ..core import metrics

# Default sampling rate. 67 Hz (15 ms period) rather than a round 100:
# prime-ish rates avoid lockstep aliasing with the pump's own periodic
# work (a 100 Hz sampler over a 10 ms-quantized loop samples the same
# phase forever and the profile lies).
DEFAULT_HZ = 67.0

# Trie bound: past this many frame nodes new stack suffixes stop
# growing the trie (counts still land on the deepest existing node) and
# the drop is surfaced in describe(). 64k nodes is ~an order of
# magnitude above what the full tier-1 suite's stacks produce.
DEFAULT_MAX_NODES = 65536

# Stack depth cap per sample: deeper frames (recursive solver descent,
# pytest internals in tests) fold into their 128-frame prefix.
MAX_STACK_DEPTH = 128

# Per-interval aggregate ring: at 10 s per interval and 180 slots the
# ring holds 30 minutes of "what was hot then" history.
DEFAULT_INTERVAL_S = 10.0
DEFAULT_RING_SLOTS = 180

THREAD_NAME = "profile-sampler"

# Thread-name substring -> role label, first match wins. Order matters:
# the sampler must recognize (and skip) itself before the generic
# "sampler" suffix match, and explicit names beat the CPython default
# "Thread-N" handler pattern.
_ROLE_PATTERNS = (
    (THREAD_NAME, "profiler"),
    ("telemetry-sampler", "sampler"),
    ("pump", "pump"),
    ("replic", "replication"),
    ("shard-supervisor", "replication"),
    ("drain", "drain"),
    ("Thread-", "handler"),
    ("MainThread", "main"),
)


def thread_role(name: str) -> str:
    for pattern, role in _ROLE_PATTERNS:
        if pattern in name:
            return role
    return "other"


def _frame_label(frame) -> str:
    """Stable per-function label: ``path/tail.py:function``. Aggregating
    by function (not line) keeps the trie small and the flamegraph
    readable; co_filename is trimmed to its last two components so
    labels survive venv/site-packages prefix churn across hosts."""
    code = frame.f_code
    parts = code.co_filename.replace("\\", "/").rsplit("/", 2)
    tail = "/".join(parts[-2:])
    return f"{tail}:{code.co_name}"


class _Node:
    __slots__ = ("children", "self_count", "total_count")

    def __init__(self):
        self.children: dict[str, _Node] = {}
        self.self_count = 0
        self.total_count = 0


class StackProfiler:
    """Bounded folding-trie stack sampler with an injectable clock.

    Live path: ``start()`` spawns the daemon sampler thread; each pass
    snapshots ``sys._current_frames()``, resolves thread names to roles,
    and folds every stack (outermost frame first) under its role root.
    Deterministic path: ``sample(now=..., frames=[(name, [label, ...]),
    ...])`` performs one synchronous pass with synthetic stacks and an
    explicit timestamp — the tests' only entry point.
    """

    def __init__(self, hz: float = DEFAULT_HZ, clock=None,
                 max_nodes: int = DEFAULT_MAX_NODES,
                 interval_s: float = DEFAULT_INTERVAL_S,
                 ring_slots: int = DEFAULT_RING_SLOTS):
        self.hz = max(float(hz), 0.1)
        # time.monotonic, not time.time: interval bookkeeping is latency
        # measurement, never decision state, and must not jump with NTP.
        self.clock = clock if clock is not None else time.monotonic
        self.max_nodes = max_nodes
        self.interval_s = interval_s
        self._root = _Node()  # guarded-by: _data_lock
        self._node_count = 0  # guarded-by: _data_lock
        self._dropped_frames = 0  # guarded-by: _data_lock
        self._samples = 0  # guarded-by: _data_lock
        self._interval_counts: dict[str, int] = {}  # guarded-by: _data_lock
        self._interval_start: float | None = None  # guarded-by: _data_lock
        self._interval_samples = 0  # guarded-by: _data_lock
        self._ring: deque = deque(maxlen=ring_slots)  # guarded-by: _data_lock
        self._data_lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        metrics.profile_trie_nodes.bind(self, StackProfiler._collect_nodes)

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "StackProfiler":
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name=THREAD_NAME, daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(timeout=5.0)

    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def _run(self) -> None:
        period = 1.0 / self.hz
        busy = 0.0
        while not self._stop.wait(max(0.0, period - busy)):
            t0 = time.perf_counter()
            try:
                self.sample()
            except Exception:
                # A torn frame snapshot (thread died mid-walk) must not
                # kill the sampler; the next pass resamples.
                metrics.telemetry_tick_errors_total.inc("profile_sample")
            busy = time.perf_counter() - t0
            if busy > period:
                metrics.profile_overruns_total.inc()

    def _collect_nodes(self) -> float:
        with self._data_lock:
            return float(self._node_count)

    # -- sampling ----------------------------------------------------------

    def sample(self, now: float | None = None, frames=None) -> int:
        """One sampler pass. Returns the number of stacks folded."""
        if now is None:
            now = self.clock()
        if frames is None:
            frames = self._live_frames()
        folded = 0
        with self._data_lock:
            if self._interval_start is None:
                self._interval_start = now
            elif now - self._interval_start >= self.interval_s:
                self._roll_interval_locked(now)
            for name, stack in frames:
                role = thread_role(name)
                if role == "profiler":
                    continue
                self._fold_locked(role, stack)
                folded += 1
            self._samples += folded
            self._interval_samples += folded
        if folded:
            metrics.profile_samples_total.inc(amount=float(folded))
        return folded

    def _live_frames(self) -> list[tuple[str, list[str]]]:
        names = {t.ident: t.name for t in threading.enumerate()}
        out = []
        for tid, frame in sys._current_frames().items():
            stack: list[str] = []
            f = frame
            while f is not None and len(stack) < MAX_STACK_DEPTH:
                stack.append(_frame_label(f))
                f = f.f_back
            stack.reverse()  # outermost first: trie roots at thread entry
            out.append((names.get(tid, f"thread-{tid}"), stack))
        out.sort()
        return out

    def _fold_locked(self, role: str, stack) -> None:
        node = self._root
        node.total_count += 1
        for label in (role, *stack):
            child = node.children.get(label)
            if child is None:
                if self._node_count >= self.max_nodes:
                    # Bounded: credit the deepest existing node's self
                    # time and record the truncation.
                    self._dropped_frames += 1
                    break
                child = node.children[label] = _Node()
                self._node_count += 1
            node = child
            node.total_count += 1
        node.self_count += 1
        leaf = stack[-1] if stack else role
        self._interval_counts[f"{role};{leaf}"] = (
            self._interval_counts.get(f"{role};{leaf}", 0) + 1
        )

    def _roll_interval_locked(self, now: float) -> None:
        top = sorted(
            self._interval_counts.items(), key=lambda kv: (-kv[1], kv[0])
        )[:10]
        self._ring.append({
            "start": self._interval_start,
            "end": now,
            "samples": self._interval_samples,
            "top": [{"frame": k, "self": v} for k, v in top],
        })
        self._interval_counts = {}
        self._interval_start = now
        self._interval_samples = 0

    # -- read surface ------------------------------------------------------

    def folded(self) -> str:
        """flamegraph.pl input: one ``role;frame;...;frame count`` line
        per trie path with nonzero self count, deterministically sorted."""
        lines: list[str] = []
        with self._data_lock:
            stack: list[tuple[_Node, tuple[str, ...]]] = [(self._root, ())]
            while stack:
                node, path = stack.pop()
                if node.self_count and path:
                    lines.append(f"{';'.join(path)} {node.self_count}")
                for label in sorted(node.children, reverse=True):
                    stack.append((node.children[label], path + (label,)))
        return "\n".join(sorted(lines))

    def top(self, n: int = 10) -> list[dict]:
        """Hottest frames by self count (total = inclusive count), the
        ``jobset-tpu top hotspots`` table."""
        agg: dict[str, list[int]] = {}
        with self._data_lock:
            stack: list[tuple[_Node, int]] = [(self._root, 0)]
            while stack:
                node, depth = stack.pop()
                for label, child in node.children.items():
                    # depth 1 == the role root; skip it in the frame table.
                    if depth >= 1:
                        row = agg.setdefault(label, [0, 0])
                        row[0] += child.self_count
                        row[1] += child.total_count
                    stack.append((child, depth + 1))
            samples = self._samples
        rows = [
            {"frame": label, "self": s, "total": t,
             "self_pct": round(100.0 * s / samples, 2) if samples else 0.0}
            for label, (s, t) in agg.items()
        ]
        rows.sort(key=lambda r: (-r["self"], -r["total"], r["frame"]))
        return rows[:n]

    def roles(self) -> dict[str, int]:
        """Samples folded under each thread-role root, sorted by role."""
        with self._data_lock:
            return {
                label: child.total_count
                for label, child in sorted(self._root.children.items())
            }

    def describe(self, top_n: int = 25) -> dict:
        """``GET /debug/profile`` payload."""
        with self._data_lock:
            samples = self._samples
            nodes = self._node_count
            dropped = self._dropped_frames
            intervals = list(self._ring)
        return {
            "running": self.running,
            "hz": self.hz,
            "interval_s": self.interval_s,
            "samples": samples,
            "trie_nodes": nodes,
            "max_nodes": self.max_nodes,
            "dropped_frames": dropped,
            "roles": self.roles(),
            "top": self.top(top_n),
            "folded": self.folded(),
            "intervals": intervals,
        }

    def reset(self) -> None:
        with self._data_lock:
            self._root = _Node()
            self._node_count = 0
            self._dropped_frames = 0
            self._samples = 0
            self._interval_counts = {}
            self._interval_start = None
            self._interval_samples = 0
            self._ring.clear()


# -- JIT/kernel observability ---------------------------------------------


class KernelCacheRegistry:
    """Named ``lru_cache`` handles of the compile-once kernel factories,
    bound to the ``jobset_jit_cache_{hits,misses}`` callback gauges so a
    scrape reads live ``cache_info()`` — no push sites to forget."""

    def __init__(self):
        self._caches: dict[str, object] = {}  # guarded-by: _lock
        self._lock = threading.Lock()

    def register(self, kernel: str, cached_factory) -> None:
        with self._lock:
            self._caches[kernel] = cached_factory
        # (Re)bind on every registration: metrics.reset() in test
        # teardown drops bindings, and the next factory import restores
        # them here.
        metrics.jit_cache_hits.bind(self, KernelCacheRegistry._hits)
        metrics.jit_cache_misses.bind(self, KernelCacheRegistry._misses)

    def _info(self) -> list[tuple[str, object]]:
        with self._lock:
            items = sorted(self._caches.items())
        out = []
        for kernel, factory in items:
            info = getattr(factory, "cache_info", None)
            if info is not None:
                out.append((kernel, info()))
        return out

    def _hits(self) -> list[tuple[tuple, float]]:
        return [((kernel,), float(info.hits))
                for kernel, info in self._info()]

    def _misses(self) -> list[tuple[tuple, float]]:
        return [((kernel,), float(info.misses))
                for kernel, info in self._info()]

    def snapshot(self) -> dict[str, dict]:
        """Per-kernel cache stats for /debug/profile consumers."""
        return {
            kernel: {
                "hits": info.hits, "misses": info.misses,
                "maxsize": info.maxsize, "currsize": info.currsize,
            }
            for kernel, info in self._info()
        }


KERNEL_CACHES = KernelCacheRegistry()


def timed_compile(kernel: str, fn):
    """Wrap a freshly-jitted kernel so its first invocation — the one
    that traces, lowers, and compiles — is timed into
    ``jobset_jit_compile_seconds{kernel}`` and counted in
    ``jobset_jit_compiles_total{kernel}``. Factories call this per
    specialization (inside the lru_cached body), so every bucket miss
    surfaces its real compile cost; steady-state calls pay one boolean
    check."""
    state = {"pending": True}
    lock = threading.Lock()

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        with lock:
            first, state["pending"] = state["pending"], False
        if not first:
            return fn(*args, **kwargs)
        t0 = time.perf_counter()
        out = fn(*args, **kwargs)
        _block(out)
        elapsed = time.perf_counter() - t0
        metrics.jit_compiles_total.inc(kernel)
        metrics.jit_compile_seconds.observe(elapsed, kernel)
        return out

    return wrapper


_SEEN_SHAPES: dict[str, set] = {}  # guarded-by: _SEEN_LOCK
_SEEN_LOCK = threading.Lock()


def jit_shape_call(kernel: str, fn, *args, **kwargs):
    """Call a module-level ``@jax.jit`` kernel, treating the first call
    per (shapes, dtypes, kwargs) signature as its compile — the same key
    jax's own compilation cache uses — and timing it into the
    ``jobset_jit_*`` families. The lru_cached bucket factories use
    :func:`timed_compile` instead (one fresh callable per
    specialization); this is for kernels whose cache lives inside
    ``jax.jit`` itself (the solver's module-level auctions). Host-side
    call sites only: inside a trace the side effects would replay."""
    sig_parts: list = []
    for a in args:
        shape = getattr(a, "shape", None)
        if shape is not None:
            sig_parts.append((tuple(shape), str(getattr(a, "dtype", ""))))
        else:
            sig_parts.append(repr(a))
    sig = (tuple(sig_parts), tuple(sorted(kwargs.items())))
    with _SEEN_LOCK:
        seen = _SEEN_SHAPES.setdefault(kernel, set())
        first = sig not in seen
        seen.add(sig)
    if not first:
        return fn(*args, **kwargs)
    t0 = time.perf_counter()
    out = fn(*args, **kwargs)
    _block(out)
    elapsed = time.perf_counter() - t0
    metrics.jit_compiles_total.inc(kernel)
    metrics.jit_compile_seconds.observe(elapsed, kernel)
    return out


def _block(out) -> None:
    """Best-effort device sync so first-call timing covers the compile
    AND its execution rather than the async dispatch. Duck-typed: no
    jax import here (the factories gate jax themselves)."""
    if isinstance(out, (tuple, list)):
        for item in out:
            _block(item)
        return
    block = getattr(out, "block_until_ready", None)
    if callable(block):
        try:
            block()
        except Exception:
            pass


def note_transfer(kernel: str, direction: str, *arrays) -> None:
    """Account host<->device bytes at a kernel boundary
    (``direction`` is ``h2d`` or ``d2h``), estimated from ``nbytes`` of
    the arrays actually crossing it."""
    total = 0
    for a in arrays:
        total += int(getattr(a, "nbytes", 0) or 0)
    if total:
        metrics.jit_transfer_bytes_total.inc(
            kernel, direction, amount=float(total)
        )
