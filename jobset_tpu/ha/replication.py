"""Quorum WAL replication: the Raft-style layer over the durable store.

The store PR made ONE controller crash-safe (fsync'd CRC-framed WAL +
snapshots); this module makes the control plane survive losing that
controller's whole node. N replicas form a quorum:

* the **leader** (elected via `core.lease.LeaderElector`, whose fencing
  term is the replication epoch) appends each commit record to its own WAL
  as before, then streams the identical canonical payload to every
  follower and counts fsync acknowledgements — an HTTP write is
  acknowledged to the client only once a MAJORITY of replicas (leader
  included) has the frame on disk (`Store.commit_seq`, the commit index);
* each **follower** runs a `FollowerLog`: an append-only mirror of the
  leader's WAL in a standard store data-dir layout (wal.log +
  snapshot.json + meta.json), so a follower that wins election simply
  opens a `Store` on its directory and `Store.recover` replays its
  committed log into a fresh `Cluster` — the exact crash-restart path the
  store PR proved, now fed by replication instead of local history;
* **fencing**: every append-entries call carries the leader's lease term;
  a follower that has observed term N rejects appends from any term < N,
  so a deposed leader (stalled, partitioned) cannot commit into the new
  leader's epoch. The rejected leader marks itself `fenced` and the
  server steps it down;
* **catch-up**: a replica promoting (or rejoining after a crash) first
  reconciles its log against a quorum — it asks every reachable peer for
  its (term, lastSeq) position, requires that itself plus the reachable
  peers form a majority, and copies the missing tail (or a full snapshot
  when the source's resend buffer no longer covers the gap) from the most
  up-to-date peer. Per-record terms (stamped by `Store.commit`) let it
  detect a divergent unacknowledged tail left by a dead leader and
  truncate it before appending the quorum's version.

Why zero majority-acknowledged writes can be lost: an acknowledged frame
is fsync'd on >= majority of N replicas; after losing any single replica,
every majority of the survivors intersects that set, so the catch-up
step's "most up-to-date reachable peer" always holds the frame.

Chaos: each leader->follower ship is one arrival at the
``replication.stream`` injection point (`break` drops the call before any
bytes move, `latency` delays it), so seeded kill-storms exercise follower
lag, resend, and quorum loss deterministically.
"""

from __future__ import annotations

import json
import os
import threading
from collections import deque
from typing import Optional

from ..store.codec import canonical
from ..store.store import (
    SNAPSHOT_FILE,
    WAL_FILE,
    StoreError,
    write_snapshot_file,
)
from ..store.wal import StoreWriteError, WriteAheadLog

META_FILE = "meta.json"


class ReplicationError(Exception):
    """Base class for replication failures."""


class NoQuorumError(ReplicationError):
    """Fewer than a majority of replicas are reachable: promotion (or any
    operation that must prove it sees every acknowledged write) must not
    proceed."""


def majority_of(cluster_size: int) -> int:
    return cluster_size // 2 + 1


def _entry_term(entry: dict) -> int:
    """Fencing term stamped inside an entry's record payload (0 for
    records written by an unreplicated store)."""
    try:
        return int(json.loads(entry["payload"]).get("term", 0))
    except (ValueError, KeyError, TypeError):
        return 0


# ---------------------------------------------------------------------------
# Follower side: the replication receiver
# ---------------------------------------------------------------------------


class FollowerLog:
    """Append-only mirror of the leader's WAL in a standard store data-dir.

    Layout is exactly `Store`'s (snapshot.json + wal.log, same CRC frames,
    same exclusive LOCK flock) plus `meta.json` carrying the durable
    fencing term — so promotion is nothing more than `close()` followed by
    `Store(data_dir).recover(cluster)`. Appends fsync per record before
    acknowledging, which is what makes a majority of acks a durability
    guarantee rather than a liveness hint.
    """

    def __init__(self, data_dir: str, injector=None):
        os.makedirs(data_dir, exist_ok=True)
        self.data_dir = data_dir
        # Same single-writer guard as Store: a follower log and a serving
        # store must never share a directory concurrently.
        self._lock_fd = os.open(
            os.path.join(data_dir, "LOCK"), os.O_RDWR | os.O_CREAT, 0o644
        )
        try:
            import fcntl

            fcntl.flock(self._lock_fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
        except OSError as exc:
            os.close(self._lock_fd)
            self._lock_fd = None
            raise StoreError(
                f"data dir {data_dir!r} is locked by another process "
                f"(one replica per --data-dir): {exc}"
            ) from exc
        self.wal = WriteAheadLog(
            os.path.join(data_dir, WAL_FILE), injector=injector
        )
        self.snapshot_seq = 0
        self._snapshot_last_term = 0
        snapshot_path = os.path.join(data_dir, SNAPSHOT_FILE)
        if os.path.exists(snapshot_path):
            try:
                with open(snapshot_path) as f:
                    doc = json.load(f)
                self.snapshot_seq = int(doc.get("seq", 0))
                self._snapshot_last_term = int(doc.get("lastTerm", 0))
            except (OSError, ValueError):
                self.snapshot_seq = 0
        records, _torn = self.wal.recover()
        # In-memory resend/catch-up view: [{seq, payload}] of every record
        # past the snapshot, canonical strings so fetches ship the exact
        # bytes that were framed.
        self.records: list[dict] = [
            {"seq": int(r.get("seq", 0)), "payload": canonical(r)}
            for r in records
            if int(r.get("seq", 0)) > self.snapshot_seq
        ]
        self.last_seq = (
            self.records[-1]["seq"] if self.records else self.snapshot_seq
        )
        self.term = 0
        self.commit_seq = self.snapshot_seq
        meta_path = os.path.join(data_dir, META_FILE)
        meta: dict = {}
        if os.path.exists(meta_path):
            try:
                with open(meta_path) as f:
                    meta = json.load(f)
                self.term = int(meta.get("term", 0))
                self.commit_seq = min(
                    int(meta.get("commitSeq", 0)), self.last_seq
                )
            except (OSError, ValueError):
                meta = {}
        self.commit_seq = max(self.commit_seq, self.snapshot_seq)
        # Term of the LAST LOG ENTRY — the up-to-dateness rank (Raft's
        # lastLogTerm), distinct from the OBSERVED term above (Raft's
        # currentTerm, the fencing floor). Ranking replicas by observed
        # term would let a gap-rejected straggler — whose term was bumped
        # by a new leader's probe but which holds none of that epoch's
        # records — outrank a peer holding majority-acknowledged history.
        if self.records:
            self.last_entry_term = _entry_term(self.records[-1])
        else:
            self.last_entry_term = max(
                self._snapshot_last_term,
                int(meta.get("lastEntryTerm", 0)),
            )
        # Self-compaction threshold: once this many COMMITTED records
        # accumulate, fold them into snapshot.json and truncate the WAL
        # (a follower mirrors forever; without this its log and in-memory
        # record list grow without bound).
        self.compact_records = 1024
        self._lock = threading.Lock()

    # -- durability helpers -------------------------------------------------

    def seed_meta(self, term: int, commit_seq: int,
                  last_entry_term: int) -> None:
        """Durably seed the mirror's meta from a known-good position (the
        supervisor's demotion path: the Store never maintained meta.json,
        so a reopened mirror would otherwise believe commitSeq=0 and a
        later catch-up would fall back to a full snapshot install).
        Monotonic-max semantics, commit index capped at the physical log
        — the same invariants recovery derives."""
        with self._lock:
            self.term = max(self.term, int(term))
            self.commit_seq = max(
                self.commit_seq, min(int(commit_seq), self.last_seq)
            )
            self.last_entry_term = max(
                self.last_entry_term, int(last_entry_term)
            )
            self._persist_meta_locked()

    def _persist_meta_locked(self, fsync: bool = True) -> None:
        """Durably record (term, commitSeq). The TERM must survive a crash
        (Raft persists currentTerm for the same reason: a rejoining
        replica must keep rejecting leaders it already fenced out); the
        commit index is a best-effort optimization — recovery re-derives a
        safe lower bound and catch-up sharpens it."""
        path = os.path.join(self.data_dir, META_FILE)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({
                "term": self.term,
                "commitSeq": self.commit_seq,
                "lastEntryTerm": self.last_entry_term,
            }, f)
            if fsync:
                f.flush()
                os.fsync(f.fileno())
        os.replace(tmp, path)
        if fsync:
            # The rename itself must survive power loss: a term adopted
            # during establish_term that evaporates on reboot would
            # re-open the deposed epoch's window (Raft persists
            # currentTerm for exactly this reason).
            dir_fd = os.open(self.data_dir, os.O_RDONLY)
            try:
                os.fsync(dir_fd)
            finally:
                os.close(dir_fd)

    # -- replication receiver ----------------------------------------------

    def position(self) -> dict:
        with self._lock:
            return {
                "role": "follower",
                "term": self.term,
                "lastTerm": self.last_entry_term,
                "lastSeq": self.last_seq,
                "commitSeq": self.commit_seq,
            }

    def append_entries(
        self, term: int, entries: list[dict], commit_seq: int = 0
    ) -> dict:
        """One replication RPC from a leader: fence on term, append the
        in-order tail (fsync per frame), advance the commit index. Returns
        {ok, term, lastSeq}; ok=False with reason 'stale-term' fences a
        deposed leader, 'gap' asks it to resend from lastSeq+1."""
        with self._lock:
            if term < self.term:
                return {
                    "ok": False, "reason": "stale-term",
                    "term": self.term, "lastSeq": self.last_seq,
                }
            if term > self.term:
                self.term = int(term)
                self._persist_meta_locked()
            for entry in sorted(entries, key=lambda e: e["seq"]):
                seq = int(entry["seq"])
                if seq <= self.last_seq:
                    local_term = self._record_term_locked(seq)
                    if (
                        local_term is not None
                        and local_term != _entry_term(entry)
                    ):
                        # Raft's append conflict rule: same seq, different
                        # term — our version was a deposed leader's
                        # never-committed write. The current-term leader's
                        # history wins: drop ours and everything after it,
                        # then fall through to append the leader's. A
                        # blind duplicate-skip here would ACK history we
                        # do not actually hold.
                        self._truncate_from_locked(seq)
                    else:
                        continue  # true duplicate resend: idempotent
                if seq != self.last_seq + 1:
                    return {
                        "ok": False, "reason": "gap",
                        "term": self.term, "lastSeq": self.last_seq,
                    }
                payload = entry["payload"].encode()
                try:
                    self.wal.append(payload, detail=f"replica seq={seq}")
                except StoreWriteError:
                    # Local disk fault: repair the tail and report our
                    # durable position — the frame is NOT acknowledged.
                    try:
                        self.wal.repair()
                    except OSError:
                        pass
                    return {
                        "ok": False, "reason": "append-failed",
                        "term": self.term, "lastSeq": self.last_seq,
                    }
                self.records.append(
                    {"seq": seq, "payload": entry["payload"]}
                )
                self.last_seq = seq
                self.last_entry_term = _entry_term(entry)
            if commit_seq:
                self.commit_seq = max(
                    self.commit_seq, min(int(commit_seq), self.last_seq)
                )
        self.maybe_compact()
        with self._lock:
            return {
                "ok": True, "term": self.term, "lastSeq": self.last_seq,
            }

    def install_snapshot(self, term: int, doc: dict) -> dict:
        """Full-state transfer for a follower too far behind the leader's
        resend buffer: atomically replace snapshot.json, truncate the WAL,
        and fast-forward to the snapshot's seq (Store recovery treats this
        exactly like a locally-compacted log)."""
        with self._lock:
            if term < self.term:
                return {
                    "ok": False, "reason": "stale-term",
                    "term": self.term, "lastSeq": self.last_seq,
                }
            if term > self.term:
                self.term = int(term)
                self._persist_meta_locked()
            write_snapshot_file(self.data_dir, doc)
            self.wal.reset()
            self.records = []
            self.snapshot_seq = int(doc.get("seq", 0))
            self._snapshot_last_term = int(doc.get("lastTerm", 0))
            self.last_seq = self.snapshot_seq
            self.last_entry_term = self._snapshot_last_term
            self.commit_seq = max(self.commit_seq, self.snapshot_seq)
            self._persist_meta_locked()
            return {
                "ok": True, "term": self.term, "lastSeq": self.last_seq,
            }

    # -- catch-up source ----------------------------------------------------

    def entries_after(self, after_seq: int) -> dict:
        """Log tail for a peer's catch-up: records with seq > after_seq,
        preceded by the full snapshot when the gap predates our WAL."""
        with self._lock:
            if after_seq < self.snapshot_seq:
                snapshot_path = os.path.join(self.data_dir, SNAPSHOT_FILE)
                with open(snapshot_path) as f:
                    doc = json.load(f)
                return {"snapshot": doc, "entries": list(self.records)}
            return {
                "entries": [
                    e for e in self.records if e["seq"] > after_seq
                ]
            }

    def _record_term_locked(self, seq: int) -> Optional[int]:
        for e in self.records:
            if e["seq"] == seq:
                return _entry_term(e)
        return None

    def record_term(self, seq: int) -> Optional[int]:
        """Fencing term of the local record at `seq` (None when we do not
        hold it) — the divergence probe catch-up uses."""
        with self._lock:
            return self._record_term_locked(seq)

    def _truncate_from_locked(self, seq: int) -> int:
        keep = [e for e in self.records if e["seq"] < seq]
        dropped = len(self.records) - len(keep)
        if dropped:
            # In-place truncate at the exact frame boundary: a crash mid-
            # operation must never leave previously-fsync'd COMMITTED
            # records missing (reset-and-reappend would open exactly that
            # window). The WAL holds only records past the snapshot, in
            # order, so the boundary is the sum of the kept frames.
            self.wal.truncate_to(sum(
                self.wal.frame_size(e["payload"].encode()) for e in keep
            ))
            self.records = keep
            self.last_seq = (
                keep[-1]["seq"] if keep else self.snapshot_seq
            )
            self.last_entry_term = (
                _entry_term(keep[-1]) if keep
                else self._snapshot_last_term
            )
            self.commit_seq = min(self.commit_seq, self.last_seq)
        return dropped

    def truncate_from(self, seq: int) -> int:
        """Drop every local record with seq >= `seq` (a divergent
        unacknowledged tail from a dead leader) and rebuild the WAL from
        the retained prefix. Returns the number of records dropped."""
        with self._lock:
            return self._truncate_from_locked(seq)

    def maybe_compact(self, limit: Optional[int] = None) -> bool:
        """Fold the committed prefix into snapshot.json and truncate the
        WAL once `compact_records` committed records accumulate — the
        follower-side analog of `Store.compact`. Records are full diffs
        (last-writer-wins ops over the snapshot state), so folding is a
        replay; only records PAST the commit index stay in the WAL (they
        may still need divergence resolution at catch-up). Safe because
        committed records are immutable on a majority."""
        limit = self.compact_records if limit is None else limit
        with self._lock:
            committed = [
                e for e in self.records if e["seq"] <= self.commit_seq
            ]
            if limit <= 0 or len(committed) < limit:
                return False
            from ..store.store import KINDS

            snapshot_path = os.path.join(self.data_dir, SNAPSHOT_FILE)
            doc: dict = {}
            if os.path.exists(snapshot_path):
                with open(snapshot_path) as f:
                    doc = json.load(f)
            state = {
                kind: dict(doc.get("state", {}).get(kind) or {})
                for kind in KINDS
            }
            rv = int(doc.get("rv", 0))
            counters = dict(doc.get("counters") or {})
            last_term = int(doc.get("lastTerm", 0))
            membership = doc.get("membership")
            for entry in committed:
                record = json.loads(entry["payload"])
                for op in record.get("ops", ()):
                    if op[0] == "put":
                        state[op[1]][op[2]] = op[3]
                    else:
                        state[op[1]].pop(op[2], None)
                rv = int(record.get("rv", rv))
                counters = dict(record.get("counters") or counters)
                last_term = int(record.get("term", last_term))
                if "membership" in record:
                    # Membership-change records (docs/sharding.md
                    # "Replica migration") fold like any other: the last
                    # committed voting set survives compaction, so a
                    # promotion from this mirror still sees it.
                    membership = record["membership"]
            new_doc = {
                "seq": committed[-1]["seq"],
                "rv": rv,
                "counters": counters,
                "state": state,
                "lastTerm": last_term,
            }
            if membership is not None:
                new_doc["membership"] = membership
            write_snapshot_file(self.data_dir, new_doc)
            tail = [e for e in self.records if e["seq"] > self.commit_seq]
            self.wal.reset()
            for entry in tail:
                self.wal.append(
                    entry["payload"].encode(),
                    detail=f"compact keep={entry['seq']}",
                )
            self.records = tail
            self.snapshot_seq = new_doc["seq"]
            self._snapshot_last_term = last_term
            if not tail:
                self.last_entry_term = max(self.last_entry_term, last_term)
            self._persist_meta_locked()
            return True

    # -- lifecycle ----------------------------------------------------------

    def close(self) -> None:
        """Release the directory for promotion (Store re-opens it).
        Locked: the supervisor's promotion path closes this mirror while
        a straggling leader RPC may still be inside append_entries."""
        with self._lock:
            try:
                self._persist_meta_locked()
            except OSError:
                pass
            self.wal.close()
            if self._lock_fd is not None:
                os.close(self._lock_fd)
                self._lock_fd = None

    def hard_kill(self) -> None:
        """Crash simulation: drop the fds with no flush (kill -9) —
        deliberately WITHOUT _lock. A real SIGKILL does not wait for a
        mutex; serializing here would keep the simulated crash from
        ever landing inside an in-flight append's window, which is the
        exact interleaving the torn-write/rejoin chaos tests exist to
        cover."""
        # jslint: disable=RACE001 crash simulation: kill -9 must not take _lock — tearing mid-append is the point
        wal, fd, self._lock_fd = self.wal, self._lock_fd, None
        wal.abandon()
        if fd is not None:
            os.close(fd)


# ---------------------------------------------------------------------------
# Peer transports
# ---------------------------------------------------------------------------


class LocalPeer:
    """In-process transport for tests / the replica supervisor: calls the
    peer replica's replication surface directly. `target` is any object
    exposing the FollowerLog receiver methods (a FollowerLog, a Replica
    that routes by role, or a ReplicationCoordinator on a current
    leader).

    `src` names the CALLING replica for the network fault model: every
    call is one delivery over the directed (src, id) link, refused while
    the active PartitionPlan has it cut — so in-process partition
    scenarios exercise exactly the link semantics the HTTP transport
    enforces. `last_contact` (monotonic of the last successful call)
    feeds the coordinator's partition-suspicion surface."""

    def __init__(self, peer_id: str, target, src: str = "",
                 injector=None):
        self.id = peer_id
        self.target = target
        self.src = src
        self.injector = injector
        self.last_contact: Optional[float] = None

    def _resolve(self):
        from ..chaos import net as chaos_net

        chaos_net.guard(self.src, self.id, injector=self.injector)
        target = self.target
        resolved = getattr(target, "replication_surface", None)
        surface = resolved() if callable(resolved) else target
        if surface is None:
            raise ConnectionError(f"peer {self.id} is down")
        return surface

    def _done(self, result: dict) -> dict:
        import time as _t

        self.last_contact = _t.monotonic()
        return result

    def position(self, timeout: Optional[float] = None) -> dict:
        # `timeout` mirrors HttpPeer's probe signature; in-process calls
        # cannot block on a dial, so it is accepted and ignored.
        return self._done(self._resolve().position())

    def append_entries(self, term, entries, commit_seq=0) -> dict:
        return self._done(
            self._resolve().append_entries(term, entries, commit_seq)
        )

    def install_snapshot(self, term, doc) -> dict:
        return self._done(self._resolve().install_snapshot(term, doc))

    def entries_after(self, after_seq) -> dict:
        return self._done(self._resolve().entries_after(after_seq))


class HttpPeer:
    """Cross-process transport against a peer controller's `/ha/v1/*`
    endpoints (`controller --replicate --peers ...`).

    A transport failure opens a short down-window (`down_backoff_s`)
    during which further calls fail IMMEDIATELY instead of re-dialing: a
    blackholed peer would otherwise cost a full connect timeout on every
    write's quorum round (the ship loop runs under the cluster lock, so
    one dead host must not add seconds to every request). Position
    PROBES bypass the window (`probe=True`) and a successful probe
    clears it on the spot — a healed peer rejoins the quorum on the
    very next ship instead of serving out the rest of its penalty
    (which inflated quorum latency right after every heal). Lives at
    the transport so the coordinator's chaos arrivals and the
    in-process LocalPeer tests stay deterministic.

    `src` names the calling replica for the network fault model
    (chaos/net.py): every call is one delivery over the directed
    (src, address) link, refused while the active PartitionPlan has it
    cut."""

    def __init__(self, address: str, timeout: float = 5.0,
                 scheme: str = "http", down_backoff_s: float = 1.0,
                 src: str = "", injector=None):
        self.id = address
        self.address = address
        self.timeout = timeout
        self.down_backoff_s = down_backoff_s
        self.base = f"{scheme}://{address}/ha/v1"
        self.src = src
        self.injector = injector
        self.last_contact: Optional[float] = None
        self._down_until = 0.0
        self._probe_after = 0.0
        self._last_error = ""

    def _call(self, method: str, path: str, body: Optional[dict] = None,
              probe: bool = False,
              dial_timeout: Optional[float] = None) -> dict:
        import time as _t
        import urllib.error
        import urllib.request

        from ..chaos import net as chaos_net

        now = _t.monotonic()
        if now < self._down_until:
            # Probes may enter the down-window to detect a heal — but at
            # most ONE dial per backoff period: against a genuine
            # blackhole (no chaos guard to fail fast) every dial costs a
            # full connect timeout, and the ship loop probes under the
            # cluster lock, so an unthrottled bypass would reintroduce
            # the per-write stall the window exists to prevent.
            if not probe or now < self._probe_after:
                raise ConnectionError(
                    f"peer {self.id} in down-backoff: {self._last_error}"
                )
            self._probe_after = now + self.down_backoff_s
        try:
            chaos_net.guard(self.src, self.id, injector=self.injector)
        except ConnectionError as exc:
            # A cut link behaves exactly like a dead host: open the
            # down-window so the ship loop fails fast until a heal-side
            # probe proves the peer back.
            self._last_error = str(exc)
            self._down_until = _t.monotonic() + self.down_backoff_s
            raise
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(
            self.base + path, data=data, method=method,
            headers={"Content-Type": "application/json"},
        )
        try:
            t = self.timeout if dial_timeout is None else dial_timeout
            with urllib.request.urlopen(req, timeout=t) as resp:
                result = json.loads(resp.read())
                # Success — including a probe inside the down-window —
                # resets the backoff immediately: the peer is provably
                # back, no reason to keep failing fast.
                self._down_until = 0.0
                self._probe_after = 0.0
                self.last_contact = _t.monotonic()
                return result
        except urllib.error.HTTPError as exc:
            detail = exc.read()
            # The peer is UP (it answered): clear any open down-window —
            # an error status proves reachability exactly as a 2xx does —
            # and the contact counts for partition suspicion.
            self._down_until = 0.0
            self._probe_after = 0.0
            self.last_contact = _t.monotonic()
            raise ConnectionError(
                f"peer {self.id}: HTTP {exc.code} {detail[:200]!r}"
            ) from exc
        except (urllib.error.URLError, OSError, ValueError) as exc:
            self._last_error = str(exc)
            # Stamped at dial COMPLETION: a blackholed peer's connect
            # timeout can exceed the backoff period, and a start-stamped
            # throttle would already be expired by the time the dial
            # fails — re-dialing on every probe.
            self._down_until = _t.monotonic() + self.down_backoff_s
            self._probe_after = _t.monotonic() + self.down_backoff_s
            raise ConnectionError(f"peer {self.id}: {exc}") from exc

    @property
    def in_down_window(self) -> bool:
        """True while the peer is inside its down-backoff window (known
        dark). The pump heartbeat skips such peers — its job is keeping
        QUIET HEALTHY links fresh, and dialing a blackhole from the pump
        thread would stall reconcile by a connect timeout per window;
        heal detection stays with the ship/read paths' own probes."""
        import time as _t

        return _t.monotonic() < self._down_until

    def position(self, timeout: Optional[float] = None) -> dict:
        # The probe path: may dial inside the down-window (throttled to
        # one dial per backoff period) so a healed peer's next probe
        # re-admits it instantly instead of serving out the penalty.
        # `timeout` lets LATENCY-SENSITIVE callers (the pump heartbeat,
        # the read fence's confirm_quorum) dial short — a blackholed
        # connect on the renew thread must never outlast the lease —
        # while catch_up/promotion keep the operator's full peer
        # timeout for slow-but-healthy links.
        return self._call("GET", "/position", probe=True,
                          dial_timeout=timeout)

    def append_entries(self, term, entries, commit_seq=0) -> dict:
        return self._call("POST", "/append", {
            "term": term, "entries": entries, "commitSeq": commit_seq,
        })

    def install_snapshot(self, term, doc) -> dict:
        return self._call("POST", "/snapshot", {"term": term, "snapshot": doc})

    def entries_after(self, after_seq) -> dict:
        return self._call("GET", f"/log?after={int(after_seq)}")


# ---------------------------------------------------------------------------
# Leader side: the replication coordinator
# ---------------------------------------------------------------------------


class ReplicationCoordinator:
    """Leader-side frame shipper + commit-index bookkeeper.

    Bound to the leader's `Store` (`bind`), it is called synchronously
    from the server's commit path (under the cluster lock, exactly where
    the local fsync already happens): `replicate()` streams the new record
    to every peer, counts fsync acks, and advances `Store.commit_seq` only
    on majority. Slow/broken followers are caught up from a bounded resend
    buffer (or a snapshot install when they fall past it) on the next
    ship. Repeated quorum failure (or a term rejection from any follower)
    marks the coordinator `lost_quorum`/`fenced`, which the server turns
    into a stepdown — a leader that cannot commit must stop accepting
    writes so clients fail over to the side that can.
    """

    RESEND_BUFFER = 4096

    def __init__(
        self,
        identity: str,
        peers: list,
        term: int = 0,
        stepdown_after: int = 5,
        injector=None,
        learners: Optional[list] = None,
    ):
        self.identity = identity
        self.peers = list(peers)
        # Non-voting learner peers (docs/sharding.md "Replica
        # migration"): shipped every frame exactly like voters but NEVER
        # counted toward quorum — cluster_size/majority see voters only,
        # so a learner can lag, stall, or die without moving the commit
        # index or the stepdown math.
        self.learners = list(learners or [])
        self.term = int(term)
        self.stepdown_after = max(1, int(stepdown_after))
        self.injector = injector
        self.store = None
        # Guards the resend buffer: replicate() appends under the cluster
        # lock while a rejoining peer's catch-up fetch reads from an HTTP
        # handler thread.
        self._buffer_lock = threading.Lock()
        self._buffer: deque = deque(maxlen=self.RESEND_BUFFER)  # guarded-by: _buffer_lock
        self._peer_next: dict[str, Optional[int]] = {}
        self._peer_acked: dict[str, int] = {}
        # Step-down flags get their own LEAF lock, never held across a
        # peer call: the commit path writes them while holding the
        # cluster lock and shipping to peers, and handler/pump threads
        # read them — guarding them with the cluster lock instead would
        # let two in-process leaders (dual-leader heal window, LocalPeer
        # transport) deadlock hold-and-wait on each other's cluster
        # locks inside append_entries.
        self._flags_lock = threading.Lock()
        self.fenced = False  # guarded-by: _flags_lock
        self.lost_quorum = False  # guarded-by: _flags_lock
        self._quorum_failures = 0  # guarded-by: _flags_lock
        # Read-fence freshness window (docs/ha.md "Consistency
        # guarantees"): a read is served only when a majority of
        # replicas was contacted within this many seconds — else the
        # ReadIndex-analog probe below must re-prove the quorum first.
        self.read_fence_age_s = 1.0
        # Operator-facing partition suspicion threshold (/debug/health):
        # a peer not contacted for this long is flagged partitionSuspected
        # BEFORE quorum loss or failover fires.
        self.suspect_after_s = 2.0
        # Per-peer heartbeat retry state: (next attempt, backoff). A
        # failed heartbeat dial backs off exponentially (capped) so an
        # idle leader with a blackholed peer does not block its pump
        # thread on a connect timeout every down-window expiry.
        self._heartbeat_retry: dict[str, tuple[float, float]] = {}
        # Dial timeout for the fence/heartbeat position probes (server
        # construction clamps it below the lease): these run on the
        # lease-renewal cadence, where a blackholed peer's full connect
        # timeout could expire the lease and force a spurious stepdown
        # of a quorate leader. catch_up/ship keep the full peer timeout.
        self.probe_timeout_s = 1.0

    def _mark_fenced(self) -> None:
        """A probe/ack revealed a higher term: fence this leader."""
        with self._flags_lock:
            self.fenced = True

    @property
    def cluster_size(self) -> int:
        return len(self.peers) + 1

    @property
    def majority(self) -> int:
        return majority_of(self.cluster_size)

    def bind(self, store) -> None:
        """Attach to the leader's store: from here on, local commits are
        NOT the commit point — the quorum is."""
        self.store = store
        store.replicated = True
        store.term = self.term

    # -- the hot path -------------------------------------------------------

    def _ship(self, peer, target_seq: int) -> bool:
        """Bring one peer up to `target_seq`; True when the peer has
        fsync-acknowledged every frame through it."""
        from ..chaos.injector import consult

        fault = consult(
            "replication.stream", f"-> {peer.id}", injector=self.injector
        )
        if fault is not None:
            # break / any error kind: the stream drops pre-flight
            # (latency was already applied in place by consult).
            self._peer_next[peer.id] = None
            return False
        from ..core import metrics

        try:
            next_seq = self._peer_next.get(peer.id)
            if next_seq is None:
                pos = peer.position()
                if int(pos.get("term", 0)) > self.term:
                    self._mark_fenced()
                    return False
                # First contact (or contact after a failure): the peer's
                # lastSeq alone cannot be trusted past OUR commit index —
                # a rejoined replica may hold a dead leader's ghost
                # record at those seqs, and counting its empty-batch ack
                # would credit a quorum for head records it does not hold
                # (Raft's log-matching check is what this stands in
                # for). So the UNACKED head (commit, target] is always
                # physically shipped — the peer's append conflict rule
                # then guarantees honest possession. Records <= our
                # commit are already on a majority regardless of this
                # peer; a ghost it holds down there is reconciled by its
                # own promotion-time catch-up, not by the hot path.
                next_seq = min(
                    int(pos.get("lastSeq", 0)), self.store.commit_seq
                ) + 1
            with self._buffer_lock:
                batch = [e for e in self._buffer if e["seq"] >= next_seq]
            if (batch and batch[0]["seq"] > next_seq) or (
                not batch and next_seq <= target_seq
            ):
                # The peer's gap predates the resend buffer: full-state
                # transfer, then stream whatever the snapshot missed. A
                # snapshot may only ever cover COMMITTED history (folding
                # destroys the per-record terms divergence detection
                # needs), so while unacked records exist the install is
                # DEFERRED — the idle pump re-ships until commit catches
                # up, which cannot need this very peer: the unacked
                # suffix is bounded by stepdown_after, far inside the
                # resend buffer, so quorum-critical peers are always
                # reachable by plain record resend.
                if self.store.commit_seq < self.store.seq:
                    return False
                resp = peer.install_snapshot(
                    self.term, self.store.snapshot_doc()
                )
                if not resp.get("ok"):
                    # Fence ONLY on a genuinely higher term: a deposed
                    # ex-leader's surface also answers "stale-term" —
                    # carrying its own LOWER term — and must not scare
                    # the legitimate leader into stepping down.
                    if int(resp.get("term", 0)) > self.term:
                        self._mark_fenced()
                    self._peer_next[peer.id] = None
                    return False
                next_seq = int(resp["lastSeq"]) + 1
                with self._buffer_lock:
                    batch = [
                        e for e in self._buffer if e["seq"] >= next_seq
                    ]
            resp = peer.append_entries(
                self.term, batch, commit_seq=self.store.commit_seq
            )
            if not resp.get("ok"):
                if int(resp.get("term", 0)) > self.term:
                    self._mark_fenced()
                # gap / append-failed: force a fresh position probe next
                # ship — the probe's log-matching rule decides where to
                # resend from (the raw reported lastSeq could include a
                # not-yet-truncated ghost suffix).
                self._peer_next[peer.id] = None
                return False
            acked = int(resp["lastSeq"])
            self._peer_next[peer.id] = acked + 1
            prev = self._peer_acked.get(peer.id, 0)
            self._peer_acked[peer.id] = acked
            if acked > prev:
                metrics.ha_replicated_records_total.inc(
                    peer.id, amount=acked - prev
                )
            return acked >= target_seq
        except Exception:
            # Transport failure: re-probe the peer's position next time.
            self._peer_next[peer.id] = None
            return False

    def replicate(self, record: Optional[dict] = None,
                  payload: Optional[bytes] = None) -> bool:
        """Ship the latest committed record (default: the store's
        `last_record`) to every peer; True once a majority (self included)
        has fsync'd it — only then does the commit index advance and may
        the server acknowledge the write."""
        from ..core import metrics

        if record is None or payload is None:
            if self.store is None or self.store.last_record is None:
                return False
            record, payload = self.store.last_record
        entry = {"seq": int(record["seq"]), "payload": payload.decode()}
        with self._buffer_lock:
            if not self._buffer or self._buffer[-1]["seq"] < entry["seq"]:
                self._buffer.append(entry)
        acks = 1  # self: Store.commit already fsync'd locally
        for peer in self.peers:
            if self._ship(peer, entry["seq"]):
                acks += 1
            lag = entry["seq"] - self._peer_acked.get(peer.id, 0)
            metrics.ha_follower_lag_records.set(max(0, lag), peer.id)
        for peer in self.learners:
            # Learners ride the same ship path (position probe, resend
            # buffer, snapshot install) but their acks are observability
            # only — `acks` is untouched, so the quorum below is proven
            # over voters alone.
            self._ship(peer, entry["seq"])
            lag = entry["seq"] - self._peer_acked.get(peer.id, 0)
            metrics.shard_learner_lag_records.set(max(0, lag), peer.id)
        with self._flags_lock:
            quorum = acks >= self.majority and not self.fenced
            if quorum:
                self._quorum_failures = 0
                self.lost_quorum = False
            else:
                self._quorum_failures += 1
                if self._quorum_failures >= self.stepdown_after:
                    self.lost_quorum = True
        if quorum:
            self.store.mark_committed(entry["seq"])
            metrics.ha_commit_seq.set(self.store.commit_seq)
        else:
            metrics.ha_quorum_failures_total.inc()
        return quorum

    # -- membership (joint-consensus walk support) --------------------------

    def set_membership(self, peers: list, learners: list = ()) -> None:
        """Swap the voter/learner peer sets in one step (the supervisor's
        add-learner/promote/retire transitions). Under the store guard:
        the commit path iterates both lists while shipping, and the
        migration controller mutates them from its own step thread —
        swapping mid-ship would let a ship round count a half-applied
        voting set toward majority."""
        with self._store_guard():
            self.peers = list(peers)
            self.learners = list(learners)

    def sync_learner(self, peer_id: str) -> int:
        """Drive one ship round for the named learner and return its lag
        in records (0 = caught up to the leader's head). The promotion
        gate: a learner enters the voting set only at lag 0, so the
        joint quorum never counts a replica that could not yet prove it
        holds every acknowledged frame."""
        with self._store_guard():
            head = self.store.seq if self.store else 0
            for peer in self.learners:
                if peer.id != peer_id:
                    continue
                if self._ship(peer, head):
                    return 0
                return max(1, head - self._peer_acked.get(peer.id, 0))
        raise ReplicationError(f"no learner peer {peer_id!r} attached")

    # -- introspection / catch-up source ------------------------------------

    def _store_guard(self):
        """The cluster's RLock when the bound store has a live cluster:
        position/entries_after read Store fields (seq, commit index,
        snapshot_doc's full state) that the commit path mutates under
        that lock — an unguarded read mid-commit could hand a rejoining
        peer a torn snapshot (seq N, state N-1), which it would install
        and then skip record N forever. Reentrant, so the commit path's
        own calls are unaffected."""
        import contextlib

        cluster = getattr(self.store, "cluster", None) if self.store else None
        return cluster.lock if cluster is not None else contextlib.nullcontext()

    def position(self) -> dict:
        with self._store_guard():
            store = self.store
            return {
                "role": "leader",
                "term": self.term,
                "lastTerm": store.last_record_term if store else 0,
                "lastSeq": store.seq if store else 0,
                "commitSeq": store.commit_seq if store else 0,
            }

    def append_entries(self, term, entries, commit_seq=0) -> dict:
        """A leader is not a follower: an append from a SMALLER-or-equal
        term is a deposed peer to be fenced; a LARGER term means we are
        the deposed one — refuse and mark ourselves fenced so the server
        steps down. The fence flag goes through its leaf lock, NOT the
        cluster lock: this runs on an HTTP handler thread, and taking
        the cluster lock here while a dual leader's commit thread holds
        its own and ships to us (LocalPeer) would deadlock hold-and-wait
        across the two replicas."""
        if int(term) > self.term:
            self._mark_fenced()
        return {
            "ok": False, "reason": "stale-term",
            "term": self.term,
            "lastSeq": self.store.seq if self.store else 0,
        }

    def install_snapshot(self, term, doc) -> dict:
        return self.append_entries(term, [])

    def entries_after(self, after_seq: int) -> dict:
        with self._store_guard():
            with self._buffer_lock:
                buffered = [e for e in self._buffer if e["seq"] > after_seq]
            contiguous = (
                (buffered and buffered[0]["seq"] == after_seq + 1)
                or (not buffered and self.store.seq <= after_seq)
            )
            if contiguous:
                return {"entries": buffered}
            if self.store.commit_seq < self.store.seq:
                # Snapshots cover committed history ONLY (see _ship);
                # the fetcher retries once the quorum catches up.
                return {"entries": [], "deferred": True}
            return {"snapshot": self.store.snapshot_doc(), "entries": []}

    def health_flags(self) -> tuple[bool, bool]:
        """(fenced, lost_quorum) under their leaf lock: the pump
        thread's step-down check races the commit path's writes to
        these flags (found by the dynamic lockset harness under the
        leader-kill scenario; tests/test_race_harness.py pins the
        fix)."""
        with self._flags_lock:
            return self.fenced, self.lost_quorum

    def follower_lag(self) -> dict[str, int]:
        """Leader's view of each follower's lag in records (0 = caught
        up; 'unknown' peers have never acked). Under the store guard:
        /debug/health reads this from a handler thread while the commit
        path's _ship() advances _peer_acked under the cluster lock — the
        unguarded read was found by the dynamic lockset harness
        (tests/test_race_harness.py pins the fix)."""
        with self._store_guard():
            head = self.store.seq if self.store else 0
            return {
                peer.id: head - self._peer_acked.get(peer.id, 0)
                for peer in self.peers
            }

    # -- quorum freshness (the read fence's ReadIndex analog) ----------------

    def confirm_quorum(self, max_age_s: Optional[float] = None) -> bool:
        """True when this leader can prove a MAJORITY of replicas (self
        included) is reachable right now: peers contacted within
        `max_age_s` count as fresh; stale ones are probed (a position
        round trip — the ReadIndex analog's heartbeat). A probe that
        reveals a higher term fences us on the spot. The read fence
        serves a GET only when this holds — a quorum-partitioned leader
        must answer 503 + leader hint rather than its possibly-stale
        cluster (docs/ha.md "Consistency guarantees")."""
        import time as _t

        fenced, lost_quorum = self.health_flags()
        if fenced or lost_quorum:
            return False
        max_age = self.read_fence_age_s if max_age_s is None else max_age_s
        now = _t.monotonic()
        fresh = 1  # self
        stale = []
        for peer in self.peers:
            t = getattr(peer, "last_contact", None)
            if t is not None and now - t <= max_age:
                fresh += 1
            else:
                stale.append(peer)
        if fresh >= self.majority:
            return True
        for peer in stale:
            try:
                pos = peer.position(timeout=self.probe_timeout_s)
            except Exception:
                continue
            if int(pos.get("term", 0)) > self.term:
                self._mark_fenced()
                return False
            fresh += 1
            if fresh >= self.majority:
                return True
        return False

    def heartbeat(self, max_age_s: Optional[float] = None) -> None:
        """Leader-side contact keep-alive, driven from the pump loop: a
        caught-up quiet cluster otherwise never contacts its peers (the
        pump only re-ships when behind), so /debug/health would flag
        every link partitionSuspected on a perfectly healthy idle
        system. Probes only peers silent past half the suspicion
        threshold (bounded: HttpPeer throttles in-window probe dials to
        one per backoff period) and swallows unreachability — deciding
        suspicion is the contact report's job — but a probe that reveals
        a higher term still fences on the spot."""
        import time as _t

        fenced, lost_quorum = self.health_flags()
        if fenced or lost_quorum:
            return
        # Refresh HALF a window before the tighter of the two consumers
        # (suspicion threshold, read-fence freshness): background
        # refresh must keep idle-period GETs on confirm_quorum's cached
        # fast path, not just keep suspicion quiet.
        max_age = (
            min(self.suspect_after_s, self.read_fence_age_s) / 2.0
            if max_age_s is None else max_age_s
        )
        now = _t.monotonic()
        for peer in self.peers:
            t = getattr(peer, "last_contact", None)
            if t is not None and now - t <= max_age:
                continue
            if getattr(peer, "in_down_window", False):
                # Known dark: a dial would stall the pump thread for a
                # connect timeout and cannot refresh contact anyway.
                # The link stays (correctly) suspected; the ship/read
                # paths' own throttled probes detect the heal.
                continue
            next_try, backoff = self._heartbeat_retry.get(
                peer.id, (0.0, 0.0)
            )
            if now < next_try:
                continue
            try:
                pos = peer.position(timeout=self.probe_timeout_s)
            except Exception:
                # Exponential failure backoff (capped): a blackholed
                # dial costs a full connect timeout on the pump thread,
                # so repeat attempts must get rarer, not periodic.
                backoff = min(
                    max(backoff * 2, self.suspect_after_s * 2), 60.0
                )
                self._heartbeat_retry[peer.id] = (
                    _t.monotonic() + backoff, backoff
                )
                continue
            self._heartbeat_retry.pop(peer.id, None)
            if int(pos.get("term", 0)) > self.term:
                self._mark_fenced()
                return

    def contact_report(self) -> dict[str, dict]:
        """Per-peer contact ages for /debug/health: when was each peer
        last successfully reached (any transport-level success — even a
        term rejection proves the link), and is a partition suspected on
        its link (never contacted, or silent past `suspect_after_s`)?
        Surfaces a cut link to operators BEFORE quorum loss or failover
        fires."""
        import time as _t

        now = _t.monotonic()
        report: dict[str, dict] = {}
        for peer in self.peers:
            t = getattr(peer, "last_contact", None)
            age = None if t is None else max(0.0, now - t)
            report[peer.id] = {
                "lastContactAgeSeconds": (
                    None if age is None else round(age, 3)
                ),
                "partitionSuspected": (
                    age is None or age > self.suspect_after_s
                ),
            }
        return report


# ---------------------------------------------------------------------------
# Catch-up (promotion / rejoin)
# ---------------------------------------------------------------------------


def establish_term(term: int, peers: list,
                   cluster_size: Optional[int] = None) -> dict:
    """Raft's new-leader term assertion, run BEFORE catch-up: broadcast
    `term` to every peer with an empty append-entries. A follower that
    acks has durably adopted the term and rejects the deposed leader's
    appends from that instant — so when catch-up then reads peer
    positions, nothing can sneak into the OLD epoch between the read and
    the takeover (the race that would let a stalled ex-leader collect a
    spurious quorum behind the new leader's back). Requires follower acks
    from a majority (self included); NoQuorumError otherwise. A stalled
    ex-leader's own surface answers stale-term and fences itself — which
    is exactly the point."""
    size = cluster_size if cluster_size is not None else len(peers) + 1
    need = majority_of(size)
    acks = 1  # self
    for peer in peers:
        try:
            resp = peer.append_entries(int(term), [], commit_seq=0)
        except Exception:
            continue
        if resp.get("ok"):
            acks += 1
    if acks < need:
        raise NoQuorumError(
            f"term {term} acknowledged by only {acks}/{size} replicas "
            f"(majority {need}): refusing to promote"
        )
    return {"acks": acks}


def catch_up(log: FollowerLog, peers: list,
             cluster_size: Optional[int] = None) -> dict:
    """Reconcile a replica's log against a quorum before it may serve.

    Requires self + reachable peers >= majority (else NoQuorumError: we
    cannot prove we would see every acknowledged write). Copies the
    missing tail — or a snapshot plus tail — from the most up-to-date
    reachable peer, after truncating any divergent local suffix (records
    whose per-entry term disagrees with the quorum's: the
    unacknowledged leftovers of a dead leader). Returns stats for the
    log/metrics."""
    size = cluster_size if cluster_size is not None else len(peers) + 1
    need = majority_of(size)
    positions: list[tuple[object, dict]] = []
    for peer in peers:
        try:
            positions.append((peer, peer.position()))
        except Exception:
            continue
    if 1 + len(positions) < need:
        raise NoQuorumError(
            f"only {1 + len(positions)}/{size} replicas reachable "
            f"(majority {need}): refusing to promote/serve"
        )
    stats = {
        "peersReached": len(positions),
        "source": None,
        "records": 0,
        "truncated": 0,
        "snapshotInstalled": False,
    }
    if not positions:
        return stats  # single-replica "cluster": nothing to reconcile

    def rank(pos: dict) -> tuple[int, int]:
        # Up-to-dateness is (last ENTRY term, last seq) — Raft's
        # lastLogTerm rule. Ranking by the OBSERVED term would let a
        # gap-rejected straggler (term bumped by a new leader's probe,
        # none of that epoch's records) outrank a peer holding
        # majority-acknowledged history, losing acknowledged writes.
        return (
            int(pos.get("lastTerm", pos.get("term", 0))),
            int(pos.get("lastSeq", 0)),
        )

    best_peer, best = max(positions, key=lambda p: rank(p[1]))
    # Term to stamp on local appends: catch-up is a self-initiated PULL,
    # so it must clear our own fencing floor (observed terms never
    # decrease) while adopting the source's if higher.
    best_term = max(int(best.get("term", 0)), log.term)
    best_last_term, best_seq = rank(best)
    if (best_last_term, best_seq) <= (log.last_entry_term, log.last_seq):
        # We are at least as up to date as any reachable peer; our tail
        # (possibly holding the dead leader's unacked records) is adopted
        # and will be committed by our first post-promotion replicate —
        # the Raft convention for prior-term entries.
        return stats
    # Fetch from the last point both sides are guaranteed to agree on:
    # our commit index (majority-acknowledged records are immutable).
    base = min(log.commit_seq, log.last_seq)
    payload = best_peer.entries_after(base)
    if payload.get("deferred"):
        # The source is a leader mid-quorum-catch-up: its snapshot would
        # fold unacked records. Fail the reconciliation; the caller
        # retries once the source's commit index advances.
        raise ReplicationError(
            f"catch-up source {getattr(best_peer, 'id', '?')} deferred "
            f"its snapshot (uncommitted suffix); retry"
        )
    snapshot = payload.get("snapshot")
    if snapshot is not None:
        stats["truncated"] += log.truncate_from(
            int(snapshot.get("seq", 0)) + 1
        )
        log.install_snapshot(best_term, snapshot)
        stats["snapshotInstalled"] = True
    entries = payload.get("entries") or []
    for entry in sorted(entries, key=lambda e: e["seq"]):
        seq = int(entry["seq"])
        if seq <= log.last_seq:
            local_term = log.record_term(seq)
            if local_term is not None and local_term != _entry_term(entry):
                # Divergent suffix: ours was never majority-acknowledged
                # (the quorum's version at this seq carries a different
                # term) — drop it and take the quorum's history.
                stats["truncated"] += log.truncate_from(seq)
            else:
                continue
        resp = log.append_entries(
            best_term, [entry], commit_seq=int(best.get("commitSeq", 0))
        )
        if not resp.get("ok"):
            raise ReplicationError(
                f"catch-up append rejected at seq {seq}: {resp}"
            )
        stats["records"] += 1
    if log.last_seq > best_seq:
        # Ghost tail beyond the quorum's log: records a dead leader wrote
        # in an OLDER term past everything the new epoch has. Keeping them
        # would make this follower skip the new leader's frames at those
        # seqs as "duplicates" and acknowledge history it does not have.
        tail_term = log.record_term(best_seq + 1) or 0
        if tail_term < best_last_term:
            stats["truncated"] += log.truncate_from(best_seq + 1)
    stats["source"] = getattr(best_peer, "id", None)
    return stats


__all__ = [
    "FollowerLog",
    "HttpPeer",
    "LocalPeer",
    "NoQuorumError",
    "ReplicationCoordinator",
    "ReplicationError",
    "catch_up",
    "majority_of",
]
