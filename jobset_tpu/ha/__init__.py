"""Replicated control plane (docs/ha.md): quorum WAL replication over the
durable store, leader failover with zero lost acknowledged writes."""

from .replication import (
    FollowerLog,
    HttpPeer,
    LocalPeer,
    NoQuorumError,
    ReplicationCoordinator,
    ReplicationError,
    catch_up,
    establish_term,
    majority_of,
)
from .supervisor import Replica, ReplicaSet

__all__ = [
    "FollowerLog",
    "HttpPeer",
    "LocalPeer",
    "NoQuorumError",
    "Replica",
    "ReplicaSet",
    "ReplicationCoordinator",
    "ReplicationError",
    "catch_up",
    "establish_term",
    "majority_of",
]
