"""In-process replica supervisor: N controller replicas in one process.

The deployment analog is N `controller --replicate` processes sharing a
lease volume; tests and `bench.py --ha` need the same topology without
process management, with every random/timing choice injectable. A
`ReplicaSet` owns N `Replica`s:

* every replica starts as a **follower**: a `FollowerLog` on its private
  data-dir, reachable by the leader through a `LocalPeer`;
* `step()` drives the election loop: the first alive, serverless replica
  whose elector acquires the lease is **promoted** — catch-up against a
  quorum, `FollowerLog.close()`, `Store.recover` into a fresh `Cluster`,
  and a real `ControllerServer` bound to the SAME serving port the
  previous leader used (clients keep one address across failovers, the
  in-process stand-in for a service VIP);
* `kill_leader()` is a crash, not a shutdown: the HTTP listener dies, the
  store is hard-killed mid-state (fds dropped, no flush, no lease
  release), and failover happens only when the lease expires — exactly
  the kill -9 the acceptance soak exercises.

Timing is injectable: a shared `FakeClock` makes lease expiry a test
decision; the real clock with sub-second lease durations gives the bench
wall-clock failover numbers.
"""

from __future__ import annotations

import os
import threading
from typing import Optional

from ..core import make_cluster, metrics
from ..core.lease import FileLease, LeaderElector
from ..store import Store
from .replication import (
    FollowerLog,
    LocalPeer,
    NoQuorumError,
    ReplicationCoordinator,
    catch_up,
    establish_term,
)


class Replica:
    """One controller replica: identity + data-dir + elector, in exactly
    one of three states — follower (FollowerLog open), leader (Store +
    coordinator + serving ControllerServer), or dead (crashed; rejoin()
    re-opens the follower log)."""

    def __init__(
        self,
        replica_id: str,
        data_dir: str,
        lease_path: str,
        clock=None,
        lease_duration: float = 1.0,
        retry_period: float = 0.2,
        injector=None,
    ):
        self.replica_id = replica_id
        self.data_dir = data_dir
        self.injector = injector
        self.log: Optional[FollowerLog] = FollowerLog(data_dir)
        self.elector = LeaderElector(
            FileLease(lease_path),
            replica_id,
            lease_duration=lease_duration,
            retry_period=retry_period,
            clock=clock,
        )
        self.server = None
        self.store: Optional[Store] = None
        self.coordinator: Optional[ReplicationCoordinator] = None
        self.alive = True

    @property
    def is_leader(self) -> bool:
        return self.alive and self.server is not None

    def replication_surface(self):
        """What a LocalPeer reaches: the coordinator while leading, the
        follower log otherwise, nothing while dead (ConnectionError)."""
        if not self.alive:
            return None
        if self.coordinator is not None:
            return self.coordinator
        return self.log


class ReplicaSet:
    """N in-process replicas, one shared lease, one stable serving port."""

    def __init__(
        self,
        base_dir: str,
        n: int = 3,
        address: str = "127.0.0.1:0",
        clock=None,
        lease_duration: float = 1.0,
        retry_period: float = 0.2,
        tick_interval: float = 0.05,
        snapshot_interval: int = 256,
        injector=None,
        cluster_factory=None,
        read_fence: bool = True,
        name_prefix: str = "replica",
        shard_id=None,
        shard_map=None,
    ):
        self.base_dir = str(base_dir)
        self.clock = clock
        self.tick_interval = tick_interval
        self.snapshot_interval = snapshot_interval
        self.injector = injector
        self.cluster_factory = cluster_factory
        # Quorum read fence on promoted leaders (docs/ha.md). False is
        # for the partition checker's teeth test ONLY: it re-opens the
        # stale-read hole so the consistency checker can prove it would
        # catch one.
        self.read_fence = read_fence
        # Sharded control plane (docs/sharding.md): the shard this group
        # owns and the map its promoted servers misroute-guard against
        # (`name_prefix` keeps replica ids — the network fault model's
        # link endpoints — distinct across co-resident shard groups).
        self.shard_id = shard_id
        self.shard_map = shard_map
        host, _, port = address.rpartition(":")
        self._host = host or "127.0.0.1"
        self.serving_port = int(port) if port else 0
        # Serializes supervision entry points (step / kill / rejoin):
        # the shard plane's background supervisor steps from its own
        # thread while a bench or scenario driver kills/rejoins from
        # another — an unserialized kill landing mid-promotion would
        # tear the replica's log/store handoff.
        self._supervise_lock = threading.Lock()
        lease_path = os.path.join(self.base_dir, "leader.lease")
        self.replicas = [
            Replica(
                f"{name_prefix}-{i}",
                os.path.join(self.base_dir, f"{name_prefix}-{i}"),
                lease_path,
                clock=clock,
                lease_duration=lease_duration,
                retry_period=retry_period,
                injector=injector,
            )
            for i in range(n)
        ]
        self._promotions = 0

    # ------------------------------------------------------------------

    def peers_for(self, replica: Replica) -> list[LocalPeer]:
        # src identity makes every peer call one delivery over the
        # directed (src, dst) link of the network fault model: a cut
        # link refuses in-process exactly as HttpPeer would cross-process.
        return [
            LocalPeer(r.replica_id, r, src=replica.replica_id,
                      injector=self.injector)
            for r in self.replicas if r is not replica
        ]

    def leader(self) -> Optional[Replica]:
        for r in self.replicas:
            if r.is_leader:
                return r
        return None

    @property
    def address(self) -> str:
        return f"{self._host}:{self.serving_port}"

    def start(self) -> "ReplicaSet":
        if self.step() is None:
            raise RuntimeError("no replica could acquire the initial lease")
        return self

    def step(self) -> Optional[Replica]:
        """One supervision round: give every serverless alive replica a
        chance to take the (absent/expired/released) lease and promote.
        Returns the current leader, if any. Deterministic: replicas are
        visited in id order, so seeded runs elect identical successors.
        Thread-safe against concurrent kill/rejoin drivers (the shard
        plane's background supervisor)."""
        with self._supervise_lock:
            return self._step_locked()

    def _step_locked(self) -> Optional[Replica]:
        current = self.leader()
        if current is not None:
            coordinator = current.coordinator
            if coordinator is not None and any(
                coordinator.health_flags()
            ):
                # A leader that stepped down (quorum lost / fenced) still
                # has a serving HTTP surface; without demotion it would
                # shadow every standby forever. Tear it back to follower
                # and fall through to the election below.
                self.demote(current)
            else:
                return current
        for replica in self.replicas:
            if not replica.alive or replica.server is not None:
                continue
            if not replica.elector.ensure():
                # Only the LOWEST-id candidate contends each round: giving
                # the next replica a same-round attempt would let the
                # expiry boundary fall between the two ensure() calls and
                # make the successor timing-dependent — seeded scenarios
                # need a deterministic winner.
                return None
            try:
                self.promote(replica)
            except NoQuorumError:
                # Cannot prove we'd see every acknowledged write: hand the
                # lease back and let the next candidate try this round.
                self._abort_promotion(replica)
                continue
            except Exception:
                # Any other promotion failure (catch-up append rejected,
                # snapshot I/O error, store open failure) must not crash
                # the supervisor while this replica holds the lease — it
                # demotes back to follower and the election retries.
                import logging

                logging.getLogger("jobset_tpu.ha").exception(
                    "promotion of %s failed; returning it to standby",
                    replica.replica_id,
                )
                self._abort_promotion(replica)
                continue
            return replica
        return None

    def _abort_promotion(self, replica: Replica) -> None:
        """Unwind a failed promotion: release the lease and restore the
        replica to a serveable follower state, whatever step it died at."""
        replica.elector.release()
        if replica.server is not None:
            replica.server.stop(release_lease=False)
            replica.server = None
        if replica.store is not None:
            replica.store.close()
            replica.store = None
        replica.coordinator = None
        if replica.log is None:
            replica.log = FollowerLog(replica.data_dir)

    def promote(self, replica: Replica) -> dict:
        """Follower -> leader: catch up against a quorum, replay the
        committed log into a fresh Cluster via Store.recover, and take
        over the serving port (resourceVersion/uid continuity comes from
        the recovered store, so pre-failover informers get 410 + relist
        exactly as the single-node restart path guarantees)."""
        from ..server import ControllerServer

        peers = self.peers_for(replica)
        # Assert the new term on a majority BEFORE reading anyone's
        # position: from here the old epoch can no longer commit, so
        # catch-up sees everything it ever acknowledged.
        establish_term(
            replica.elector.term, peers, cluster_size=len(self.replicas)
        )
        stats = catch_up(
            replica.log, peers, cluster_size=len(self.replicas),
        )
        replica.log.close()
        replica.log = None
        store = Store(
            replica.data_dir,
            snapshot_interval=self.snapshot_interval,
            injector=self.injector,
        )
        # Visible to _abort_promotion IMMEDIATELY: a promotion that
        # fails past this point must close this store (releasing its
        # data-dir flock) before the follower log can be reopened —
        # assigning only on success left the abort path leaking the
        # flock and the replica permanently unpromotable.
        replica.store = store
        cluster = (
            self.cluster_factory() if self.cluster_factory is not None
            else make_cluster()
        )
        store.recover(cluster)
        coordinator = ReplicationCoordinator(
            replica.replica_id,
            self.peers_for(replica),
            term=replica.elector.term,
            injector=self.injector,
        )
        coordinator.bind(store)
        replica.coordinator = coordinator
        server = ControllerServer(
            f"{self._host}:{self.serving_port}",
            cluster=cluster,
            tick_interval=self.tick_interval,
            elector=replica.elector,
            standby_accepts_writes=False,
            replication=coordinator,
            injector=self.injector,
            read_fence=self.read_fence,
            shard_id=self.shard_id,
            shard_map=self.shard_map,
        ).start()
        self.serving_port = server.port
        # Advertise the FULL route (scheme+host+port) in the lease record
        # from now on: a standby 503's leader hint must be followable by
        # a client that never saw this deployment's flags — and, across
        # shards, by one bounced off another shard's surface.
        replica.elector.advertise = f"http://{self._host}:{server.port}"
        replica.server = server
        self._promotions += 1
        if self._promotions > 1:
            metrics.ha_failovers_total.inc()
        return stats

    def demote(self, replica: Replica) -> None:
        """Leader -> follower (lost quorum / fenced): stop serving, close
        the store, and mirror again. The lease was already released by
        the pump's stepdown; stop(release_lease=False) keeps it that way
        even if a fresh acquisition raced in."""
        commit_seq = term = last_term = 0
        if replica.store is not None:
            commit_seq = replica.store.commit_seq
            last_term = replica.store.last_record_term
        if replica.coordinator is not None:
            term = replica.coordinator.term
        if replica.server is not None:
            replica.server.stop(release_lease=False)
            replica.server = None
        if replica.store is not None:
            replica.store.close()
            replica.store = None
        replica.coordinator = None
        replica.log = FollowerLog(replica.data_dir)
        # Seed the mirror's meta from the store's final position: the
        # Store never maintained meta.json, so without this the reopened
        # FollowerLog believes commitSeq=0 and a later catch-up falls
        # back to a full snapshot install — when in truth everything up
        # to the commit index is majority-acknowledged and only the
        # unacked suffix (the deposed epoch's ghost tail) can diverge.
        replica.log.seed_meta(term, commit_seq, last_term)

    def kill_leader(self) -> str:
        """Crash the leader: listener gone, store fds dropped mid-state,
        NO lease release — standbys take over only at lease expiry.
        Serialized against step(): a kill landing mid-promotion would
        tear the log/store handoff."""
        with self._supervise_lock:
            replica = self.leader()
            if replica is None:
                raise RuntimeError("no leader to kill")
            replica.alive = False
            replica.server.crash()
            replica.store.hard_kill()
            replica.server = None
            replica.coordinator = None
            replica.store = None
            return replica.replica_id

    def kill_follower(self) -> str:
        """Crash the first alive follower (sorted id order, so seeded
        scenarios pick identical victims): its log fds drop mid-state and
        the leader sees it as lagging until rejoin()."""
        with self._supervise_lock:
            for replica in self.replicas:
                if replica.alive and replica.server is None:
                    replica.alive = False
                    replica.log.hard_kill()
                    replica.log = None
                    return replica.replica_id
            raise RuntimeError("no follower to kill")

    def rejoin(self, replica_id: str) -> dict:
        """Bring a crashed replica back as a follower: re-open its log and
        reconcile it against the quorum (divergent unacked tail from its
        leadership, if any, is truncated here)."""
        with self._supervise_lock:
            replica = next(
                r for r in self.replicas if r.replica_id == replica_id
            )
            if replica.alive:
                raise RuntimeError(f"{replica_id} is already alive")
            replica.log = FollowerLog(replica.data_dir)
            replica.alive = True
            return catch_up(
                replica.log,
                self.peers_for(replica),
                cluster_size=len(self.replicas),
            )

    def stop(self) -> None:
        for replica in self.replicas:
            if replica.server is not None:
                try:
                    replica.server.stop()
                finally:
                    replica.server = None
            if replica.store is not None:
                replica.store.close()
                replica.store = None
            replica.coordinator = None
            if replica.log is not None:
                replica.log.close()
                replica.log = None


__all__ = ["Replica", "ReplicaSet"]
