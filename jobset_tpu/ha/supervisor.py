"""In-process replica supervisor: N controller replicas in one process.

The deployment analog is N `controller --replicate` processes sharing a
lease volume; tests and `bench.py --ha` need the same topology without
process management, with every random/timing choice injectable. A
`ReplicaSet` owns N `Replica`s:

* every replica starts as a **follower**: a `FollowerLog` on its private
  data-dir, reachable by the leader through a `LocalPeer`;
* `step()` drives the election loop: the first alive, serverless replica
  whose elector acquires the lease is **promoted** — catch-up against a
  quorum, `FollowerLog.close()`, `Store.recover` into a fresh `Cluster`,
  and a real `ControllerServer` bound to the SAME serving port the
  previous leader used (clients keep one address across failovers, the
  in-process stand-in for a service VIP);
* `kill_leader()` is a crash, not a shutdown: the HTTP listener dies, the
  store is hard-killed mid-state (fds dropped, no flush, no lease
  release), and failover happens only when the lease expires — exactly
  the kill -9 the acceptance soak exercises.

Timing is injectable: a shared `FakeClock` makes lease expiry a test
decision; the real clock with sub-second lease durations gives the bench
wall-clock failover numbers.
"""

from __future__ import annotations

import os
import threading
from typing import Optional

from ..core import make_cluster, metrics
from ..core.lease import FileLease, LeaderElector
from ..store import Store
from .replication import (
    FollowerLog,
    LocalPeer,
    NoQuorumError,
    ReplicationCoordinator,
    catch_up,
    establish_term,
)


class Replica:
    """One controller replica: identity + data-dir + elector, in exactly
    one of three states — follower (FollowerLog open), leader (Store +
    coordinator + serving ControllerServer), or dead (crashed; rejoin()
    re-opens the follower log)."""

    def __init__(
        self,
        replica_id: str,
        data_dir: str,
        lease_path: str,
        clock=None,
        lease_duration: float = 1.0,
        retry_period: float = 0.2,
        injector=None,
    ):
        self.replica_id = replica_id
        self.data_dir = data_dir
        self.injector = injector
        self.log: Optional[FollowerLog] = FollowerLog(data_dir)
        self.elector = LeaderElector(
            FileLease(lease_path),
            replica_id,
            lease_duration=lease_duration,
            retry_period=retry_period,
            clock=clock,
        )
        self.server = None
        self.store: Optional[Store] = None
        self.coordinator: Optional[ReplicationCoordinator] = None
        self.alive = True

    @property
    def is_leader(self) -> bool:
        return self.alive and self.server is not None

    def replication_surface(self):
        """What a LocalPeer reaches: the coordinator while leading, the
        follower log otherwise, nothing while dead (ConnectionError)."""
        if not self.alive:
            return None
        if self.coordinator is not None:
            return self.coordinator
        return self.log


class ReplicaSet:
    """N in-process replicas, one shared lease, one stable serving port."""

    def __init__(
        self,
        base_dir: str,
        n: int = 3,
        address: str = "127.0.0.1:0",
        clock=None,
        lease_duration: float = 1.0,
        retry_period: float = 0.2,
        tick_interval: float = 0.05,
        snapshot_interval: int = 256,
        injector=None,
        cluster_factory=None,
        read_fence: bool = True,
        name_prefix: str = "replica",
        shard_id=None,
        shard_map=None,
    ):
        self.base_dir = str(base_dir)
        self.clock = clock
        self.tick_interval = tick_interval
        self.snapshot_interval = snapshot_interval
        self.injector = injector
        self.cluster_factory = cluster_factory
        # Quorum read fence on promoted leaders (docs/ha.md). False is
        # for the partition checker's teeth test ONLY: it re-opens the
        # stale-read hole so the consistency checker can prove it would
        # catch one.
        self.read_fence = read_fence
        # Sharded control plane (docs/sharding.md): the shard this group
        # owns and the map its promoted servers misroute-guard against
        # (`name_prefix` keeps replica ids — the network fault model's
        # link endpoints — distinct across co-resident shard groups).
        self.shard_id = shard_id
        self.shard_map = shard_map
        host, _, port = address.rpartition(":")
        self._host = host or "127.0.0.1"
        self.serving_port = int(port) if port else 0
        # Serializes supervision entry points (step / kill / rejoin):
        # the shard plane's background supervisor steps from its own
        # thread while a bench or scenario driver kills/rejoins from
        # another — an unserialized kill landing mid-promotion would
        # tear the replica's log/store handoff.
        self._supervise_lock = threading.Lock()
        lease_path = os.path.join(self.base_dir, "leader.lease")
        # Kept for learner provisioning (add_learner mints replicas on
        # the same naming/lease/timing scheme the constructor used).
        self._name_prefix = name_prefix
        self._lease_path = lease_path
        self._lease_duration = lease_duration
        self._retry_period = retry_period
        self.replicas = [
            Replica(
                f"{name_prefix}-{i}",
                os.path.join(self.base_dir, f"{name_prefix}-{i}"),
                lease_path,
                clock=clock,
                lease_duration=lease_duration,
                retry_period=retry_period,
                injector=injector,
            )
            for i in range(n)
        ]
        # Joint-consensus membership state (docs/sharding.md "Replica
        # migration"): learners replicate but never vote and never
        # contend for the lease (step() visits self.replicas only);
        # retired replicas are out of the group entirely, their
        # data-dir locks released so the dirs are reusable.
        self.learners: list[Replica] = []
        self.retired: list[Replica] = []
        # Every voting set this supervisor has installed, in order — the
        # in-process mirror of Store.membership_log the verifier's
        # single-change/quorum-overlap invariants check.
        self.membership_log: list[list[str]] = [
            sorted(r.replica_id for r in self.replicas)
        ]
        self._member_seq = n
        self._promotions = 0

    # ------------------------------------------------------------------

    def peers_for(self, replica: Replica) -> list[LocalPeer]:
        # src identity makes every peer call one delivery over the
        # directed (src, dst) link of the network fault model: a cut
        # link refuses in-process exactly as HttpPeer would cross-process.
        # Lock-free snapshot read: every MUTATION of self.replicas lives
        # in a *_locked body under _supervise_lock; readers see either
        # the pre- or post-change list (CPython list reads are atomic).
        return [
            LocalPeer(r.replica_id, r, src=replica.replica_id,
                      injector=self.injector)
            for r in self.replicas if r is not replica
        ]

    def learner_peers_for(self, replica: Replica) -> list[LocalPeer]:
        """LocalPeer transports for every learner, as seen from
        `replica` (the leader): same directed-link fault model as
        peers_for, but these are handed to the coordinator's `learners`
        list — shipped, never counted."""
        return [
            LocalPeer(r.replica_id, r, src=replica.replica_id,
                      injector=self.injector)
            for r in self.learners
        ]

    def voter_ids(self) -> list[str]:
        return sorted(r.replica_id for r in self.replicas)

    def leader(self) -> Optional[Replica]:
        for r in self.replicas:
            if r.is_leader:
                return r
        return None

    @property
    def address(self) -> str:
        return f"{self._host}:{self.serving_port}"

    def start(self) -> "ReplicaSet":
        if self.step() is None:
            raise RuntimeError("no replica could acquire the initial lease")
        return self

    def step(self) -> Optional[Replica]:
        """One supervision round: give every serverless alive replica a
        chance to take the (absent/expired/released) lease and promote.
        Returns the current leader, if any. Deterministic: replicas are
        visited in id order, so seeded runs elect identical successors.
        Thread-safe against concurrent kill/rejoin drivers (the shard
        plane's background supervisor)."""
        with self._supervise_lock:
            return self._step_locked()

    def _step_locked(self) -> Optional[Replica]:
        current = self.leader()
        if current is not None:
            coordinator = current.coordinator
            if coordinator is not None and any(
                coordinator.health_flags()
            ):
                # A leader that stepped down (quorum lost / fenced) still
                # has a serving HTTP surface; without demotion it would
                # shadow every standby forever. Tear it back to follower
                # and fall through to the election below.
                self._demote_locked(current)
            else:
                return current
        # Snapshot the list: a successful promotion may adopt a durable
        # voting set recorded mid-migration (WAL membership records),
        # which edits self.replicas under our feet.
        for replica in list(self.replicas):
            if not replica.alive or replica.server is not None:
                continue
            if not replica.elector.ensure():
                # Only the LOWEST-id candidate contends each round: giving
                # the next replica a same-round attempt would let the
                # expiry boundary fall between the two ensure() calls and
                # make the successor timing-dependent — seeded scenarios
                # need a deterministic winner.
                return None
            try:
                self._promote_locked(replica)
            except NoQuorumError:
                # Cannot prove we'd see every acknowledged write: hand the
                # lease back and let the next candidate try this round.
                self._abort_promotion(replica)
                continue
            except Exception:
                # Any other promotion failure (catch-up append rejected,
                # snapshot I/O error, store open failure) must not crash
                # the supervisor while this replica holds the lease — it
                # demotes back to follower and the election retries.
                import logging

                logging.getLogger("jobset_tpu.ha").exception(
                    "promotion of %s failed; returning it to standby",
                    replica.replica_id,
                )
                self._abort_promotion(replica)
                continue
            return replica
        return None

    def _abort_promotion(self, replica: Replica) -> None:
        """Unwind a failed promotion: release the lease and restore the
        replica to a serveable follower state, whatever step it died at."""
        replica.elector.release()
        if replica.server is not None:
            replica.server.stop(release_lease=False)
            replica.server = None
        if replica.store is not None:
            replica.store.close()
            replica.store = None
        replica.coordinator = None
        if replica.log is None:
            replica.log = FollowerLog(replica.data_dir)

    def _promote_locked(self, replica: Replica) -> dict:
        """Follower -> leader: catch up against a quorum, replay the
        committed log into a fresh Cluster via Store.recover, and take
        over the serving port (resourceVersion/uid continuity comes from
        the recovered store, so pre-failover informers get 410 + relist
        exactly as the single-node restart path guarantees)."""
        from ..server import ControllerServer

        peers = self.peers_for(replica)
        # Assert the new term on a majority BEFORE reading anyone's
        # position: from here the old epoch can no longer commit, so
        # catch-up sees everything it ever acknowledged.
        establish_term(
            replica.elector.term, peers, cluster_size=len(self.replicas)
        )
        stats = catch_up(
            replica.log, peers, cluster_size=len(self.replicas),
        )
        replica.log.close()
        replica.log = None
        store = Store(
            replica.data_dir,
            snapshot_interval=self.snapshot_interval,
            injector=self.injector,
        )
        # Visible to _abort_promotion IMMEDIATELY: a promotion that
        # fails past this point must close this store (releasing its
        # data-dir flock) before the follower log can be reopened —
        # assigning only on success left the abort path leaking the
        # flock and the replica permanently unpromotable.
        replica.store = store
        cluster = (
            self.cluster_factory() if self.cluster_factory is not None
            else make_cluster()
        )
        store.recover(cluster)
        if store.membership is not None:
            # The durable voting set outranks our in-memory lists: a
            # crash mid-migration may have committed a membership record
            # (learner promoted / replica retired) whose supervisor-side
            # bookkeeping died with the old leader. Reconcile BEFORE
            # building the coordinator so its quorum math runs over the
            # voting set recovery proved.
            self._adopt_membership_locked(replica, store.membership)
        coordinator = ReplicationCoordinator(
            replica.replica_id,
            self.peers_for(replica),
            term=replica.elector.term,
            injector=self.injector,
            learners=self.learner_peers_for(replica),
        )
        coordinator.bind(store)
        replica.coordinator = coordinator
        server = ControllerServer(
            f"{self._host}:{self.serving_port}",
            cluster=cluster,
            tick_interval=self.tick_interval,
            elector=replica.elector,
            standby_accepts_writes=False,
            replication=coordinator,
            injector=self.injector,
            read_fence=self.read_fence,
            shard_id=self.shard_id,
            shard_map=self.shard_map,
        ).start()
        self.serving_port = server.port
        # Advertise the FULL route (scheme+host+port) in the lease record
        # from now on: a standby 503's leader hint must be followable by
        # a client that never saw this deployment's flags — and, across
        # shards, by one bounced off another shard's surface.
        replica.elector.advertise = f"http://{self._host}:{server.port}"
        replica.server = server
        self._promotions += 1
        if self._promotions > 1:
            metrics.ha_failovers_total.inc()
        return stats

    def _demote_locked(self, replica: Replica) -> None:
        """Leader -> follower (lost quorum / fenced): stop serving, close
        the store, and mirror again. The lease was already released by
        the pump's stepdown; stop(release_lease=False) keeps it that way
        even if a fresh acquisition raced in."""
        commit_seq = term = last_term = 0
        if replica.store is not None:
            commit_seq = replica.store.commit_seq
            last_term = replica.store.last_record_term
        if replica.coordinator is not None:
            term = replica.coordinator.term
        if replica.server is not None:
            replica.server.stop(release_lease=False)
            replica.server = None
        if replica.store is not None:
            replica.store.close()
            replica.store = None
        replica.coordinator = None
        replica.log = FollowerLog(replica.data_dir)
        # Seed the mirror's meta from the store's final position: the
        # Store never maintained meta.json, so without this the reopened
        # FollowerLog believes commitSeq=0 and a later catch-up falls
        # back to a full snapshot install — when in truth everything up
        # to the commit index is majority-acknowledged and only the
        # unacked suffix (the deposed epoch's ghost tail) can diverge.
        replica.log.seed_meta(term, commit_seq, last_term)

    def kill_leader(self) -> str:
        """Crash the leader: listener gone, store fds dropped mid-state,
        NO lease release — standbys take over only at lease expiry.
        Serialized against step(): a kill landing mid-promotion would
        tear the log/store handoff."""
        with self._supervise_lock:
            replica = self.leader()
            if replica is None:
                raise RuntimeError("no leader to kill")
            replica.alive = False
            replica.server.crash()
            replica.store.hard_kill()
            replica.server = None
            replica.coordinator = None
            replica.store = None
            return replica.replica_id

    def kill_follower(self) -> str:
        """Crash the first alive follower (sorted id order, so seeded
        scenarios pick identical victims): its log fds drop mid-state and
        the leader sees it as lagging until rejoin()."""
        with self._supervise_lock:
            for replica in self.replicas:
                if replica.alive and replica.server is None:
                    replica.alive = False
                    replica.log.hard_kill()
                    replica.log = None
                    return replica.replica_id
            raise RuntimeError("no follower to kill")

    def rejoin(self, replica_id: str) -> dict:
        """Bring a crashed replica back as a follower: re-open its log and
        reconcile it against the quorum (divergent unacked tail from its
        leadership, if any, is truncated here)."""
        with self._supervise_lock:
            replica = next(
                r for r in self.replicas if r.replica_id == replica_id
            )
            if replica.alive:
                raise RuntimeError(f"{replica_id} is already alive")
            replica.log = FollowerLog(replica.data_dir)
            replica.alive = True
            return catch_up(
                replica.log,
                self.peers_for(replica),
                cluster_size=len(self.replicas),
            )

    # ------------------------------------------------------------------
    # Joint-consensus membership (docs/sharding.md "Replica migration")
    # ------------------------------------------------------------------

    def _close_out(self, replica: Replica) -> None:
        """Release a retiring replica's process-local resources: stop
        serving, close store/log — which releases the data-dir flocks,
        so the dir is immediately reusable — and drop liveness so any
        stale LocalPeer reference gets ConnectionError."""
        if replica.server is not None:
            replica.server.stop(release_lease=False)
            replica.server = None
        if replica.store is not None:
            replica.store.close()
            replica.store = None
        replica.coordinator = None
        if replica.log is not None:
            replica.log.close()
            replica.log = None
        replica.alive = False

    def _adopt_membership_locked(
        self, leader: Replica, voters: list[str]
    ) -> None:
        """Reconcile the in-memory lists against a durable voting set
        recovered from the WAL (under _supervise_lock, from promote()):
        learners named in the set were promoted before the crash;
        voters absent from it were retired. The promoting replica
        itself is never removed — it holds the lease, and a set
        excluding it would mean its own retirement committed, in which
        case its lease would already be released."""
        target = set(voters)
        if set(self.voter_ids()) == target:
            return
        for r in [r for r in self.learners if r.replica_id in target]:
            self.learners.remove(r)
            self.replicas.append(r)
        for r in [r for r in self.replicas
                  if r.replica_id not in target and r is not leader]:
            self.replicas.remove(r)
            self._close_out(r)
            self.retired.append(r)
        self.replicas.sort(key=lambda r: r.replica_id)
        self.membership_log.append(sorted(target))

    def _commit_membership_locked(self, leader: Replica) -> bool:
        """Durably record the CURRENT voting set: install it on the
        leader's coordinator (Raft's new-configuration-applies-on-append
        rule — quorum math switches to the new set immediately), append
        one membership record to the leader's WAL, and replicate it.
        Under the leader's cluster lock so the record interleaves
        atomically with the commit path's own append+ship rounds.
        Returns the replication quorum bool."""
        voters = self.voter_ids()
        self.membership_log.append(list(voters))
        leader.coordinator.set_membership(
            self.peers_for(leader),
            self.learner_peers_for(leader),
        )
        store, coordinator = leader.store, leader.coordinator
        with store.cluster.lock:
            store.commit_membership(voters)
            return coordinator.replicate()

    def add_learner(self) -> Replica:
        """Provision a fresh replica as a non-voting learner — the first
        step of a joint-consensus home move. It mirrors the leader's log
        (the coordinator's learner ship loop) but never votes, never
        counts toward majority, and never contends for the lease
        (step() visits self.replicas only)."""
        with self._supervise_lock:
            return self._add_learner_locked()

    def _add_learner_locked(self) -> Replica:
        leader = self.leader()
        if leader is None or leader.coordinator is None:
            raise RuntimeError("add_learner requires a serving leader")
        replica_id = f"{self._name_prefix}-{self._member_seq}"
        self._member_seq += 1
        learner = Replica(
            replica_id,
            os.path.join(self.base_dir, replica_id),
            self._lease_path,
            clock=self.clock,
            lease_duration=self._lease_duration,
            retry_period=self._retry_period,
            injector=self.injector,
        )
        self.learners.append(learner)
        leader.coordinator.set_membership(
            self.peers_for(leader),
            self.learner_peers_for(leader),
        )
        return learner

    def sync_learner(self, replica_id: str) -> int:
        """One learner catch-up round via the leader's coordinator;
        returns the remaining lag in records (0 = caught up to the
        leader's head, the promotion gate)."""
        with self._supervise_lock:
            leader = self.leader()
            if leader is None or leader.coordinator is None:
                raise RuntimeError("sync_learner requires a serving leader")
            return leader.coordinator.sync_learner(replica_id)

    def promote_learner(self, replica_id: str) -> bool:
        """Learner -> voter: one single-change joint-consensus step. The
        caller has proven lag == 0 (sync_learner); consecutive voting
        sets differ by exactly one replica, so any majority of the new
        set intersects any majority of the old — quorum safety holds at
        every interleaving, including a crash before the membership
        record lands on a majority. Returns that record's quorum bool."""
        with self._supervise_lock:
            return self._promote_learner_locked(replica_id)

    def _promote_learner_locked(self, replica_id: str) -> bool:
        leader = self.leader()
        if leader is None or leader.coordinator is None:
            raise RuntimeError(
                "promote_learner requires a serving leader"
            )
        learner = next(
            (r for r in self.learners if r.replica_id == replica_id),
            None,
        )
        if learner is None:
            raise RuntimeError(f"no learner {replica_id!r} to promote")
        self.learners.remove(learner)
        self.replicas.append(learner)
        self.replicas.sort(key=lambda r: r.replica_id)
        return self._commit_membership_locked(leader)

    def retire_replica(self, replica_id: str) -> bool:
        """Remove a replica from the group — the demote-and-retire end
        of a move, or the abort-unwind of a half-done one. Learners
        detach with no membership record (they were never voters).
        Voters leave via a single-change membership record committed by
        the leader; when the retiree IS the leader it commits its own
        removal first (a Raft leader may commit an entry removing
        itself), then steps down and releases the lease so a remaining
        voter takes over. Closing the retiree releases its data-dir
        flock, so the dir is immediately reusable. Returns the
        membership record's quorum bool (True for a learner detach)."""
        with self._supervise_lock:
            return self._retire_replica_locked(replica_id)

    def _retire_replica_locked(self, replica_id: str) -> bool:
        learner = next(
            (r for r in self.learners if r.replica_id == replica_id),
            None,
        )
        if learner is not None:
            self.learners.remove(learner)
            self._close_out(learner)
            self.retired.append(learner)
            leader = self.leader()
            if leader is not None and leader.coordinator is not None:
                leader.coordinator.set_membership(
                    self.peers_for(leader),
                    self.learner_peers_for(leader),
                )
            return True
        replica = next(
            (r for r in self.replicas if r.replica_id == replica_id),
            None,
        )
        if replica is None:
            raise RuntimeError(f"no replica {replica_id!r} to retire")
        if len(self.replicas) <= 1:
            raise RuntimeError("refusing to retire the last voter")
        leader = self.leader()
        self.replicas.remove(replica)
        if replica is leader:
            ok = self._commit_membership_locked(replica)
            self._close_out(replica)
            replica.elector.release()
            self.retired.append(replica)
            return ok
        ok = True
        if leader is not None and leader.coordinator is not None:
            ok = self._commit_membership_locked(leader)
        else:
            # Leaderless: record the set in-memory only; the next
            # promotion recovers whatever membership records exist
            # and _adopt_membership reconciles the rest.
            self.membership_log.append(self.voter_ids())
        self._close_out(replica)
        self.retired.append(replica)
        return ok

    def stop(self) -> None:
        for replica in self.learners:
            if replica.log is not None:
                replica.log.close()
                replica.log = None
        for replica in self.replicas:
            if replica.server is not None:
                try:
                    replica.server.stop()
                finally:
                    replica.server = None
            if replica.store is not None:
                replica.store.close()
                replica.store = None
            replica.coordinator = None
            if replica.log is not None:
                replica.log.close()
                replica.log = None


__all__ = ["Replica", "ReplicaSet"]
