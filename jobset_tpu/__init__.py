"""jobset_tpu — a TPU-native framework with the capabilities of JobSet.

Two cooperating planes:

* **Control plane** (`jobset_tpu.api`, `jobset_tpu.core`, `jobset_tpu.placement`):
  a declarative multi-job workload API with gang lifecycle semantics —
  replicated job groups, stable per-rank network identity, all-or-nothing
  restart, success/failure/startup policies, suspend/resume, TTL cleanup and
  topology-exclusive placement.  Behavior contract mirrors the reference
  JobSet controller (see SURVEY.md for the file:line map) but the
  architecture is an event-driven reconcile core over an in-memory cluster
  state store, with placement pluggable between a greedy per-pod path and a
  batched linear-assignment solver that runs under `jax.jit` on TPU.

* **TPU plane** (`jobset_tpu.parallel`, `jobset_tpu.models`, `jobset_tpu.ops`,
  `jobset_tpu.runtime`): the in-pod workload framework — device-mesh
  bootstrap from JobSet rank identity, pjit/shard_map parallelism
  (DP/FSDP/TP/PP/EP and ring-attention sequence parallelism), a flagship
  transformer model, and orbax-style checkpoint/resume that composes with the
  control plane's gang-restart semantics.

Cross-cutting: `jobset_tpu.obs` — request-scoped tracing (W3C traceparent
across the client/server boundary, `GET /debug/traces`), structured JSON
logging, and the exemplar-carrying metrics registry in
`jobset_tpu.core.metrics` (see docs/observability.md).
"""

__version__ = "0.1.0"
