"""Fused flash-attention block kernel (Pallas/TPU).

The hot op of the workload plane: one (q-block, kv-block) step of the
online-softmax recurrence that `parallel.ring_attention` folds around the
`sp` ring. The reference framework has no numerical kernels at all
(SURVEY.md §2.2 — JobSet is an orchestrator); this is greenfield TPU work:
logits (MXU), running max/sum statistics, and the weighted-value matmul
(MXU) are fused in VMEM so the [Tq, Tk] probability matrix never
materializes in HBM.

Interface contract (shared with the jnp reference implementation):

    block_attention(q, k, v, bias) ->
        (block_max [B,H,Tq], block_sum [B,H,Tq], weighted [B,Tq,H,D])

i.e. *unnormalized* statistics, so the caller can fold many blocks (ring
steps) into one accumulator and divide once at the end.

Differentiation: `block_attention` carries a custom VJP with a
hand-written recompute backward — the standard flash-attention strategy
(the probability matrix is cheaper to recompute than to store), with every
backward matmul's operands cast to the inputs' compute dtype (bf16 on the
training path) and f32-accumulated.

GRADIENT CONTRACT: no cotangent flows through `block_max` (output 0). The
(max, sum, weighted) triple is a gauge — shifting max by d while scaling
sum/weighted by exp(-d) is the same attention state — and every supported
consumer (`merge_block_stats` folds + the final normalization) is
gauge-invariant, for which the end-to-end gradient is exact. A consumer
that reads `block_max` NON-gauge-invariantly (e.g. a max-logit
regularizer) would get a zero gradient through it; differentiate such a
statistic from raw logits instead.

Dispatch: the Pallas kernel runs when jax is on TPU (or when
`force_interpret()` is active, which is how CPU tests exercise the kernel
via the Pallas interpreter); anything else uses the jnp reference, which
XLA fuses well enough off-TPU.
"""

from __future__ import annotations

import contextlib
import functools
import os

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

NEG_INF = -1.0e30


def _tile_env(name: str, default: int) -> int:
    """Trace-time tile override (JOBSET_TPU_FLASH_TILE_Q/K): an on-chip
    tuning knob — larger tiles mean fewer grid steps and longer MXU bursts
    at the cost of VMEM residency. Values must keep TPU tiling legal
    (multiples of 128 cover both the f32 and bf16 operand layouts).

    Resolved lazily at kernel trace time, not import time: a stale or
    malformed env var must not make the whole package unimportable for
    code paths that never touch the flash kernel, and lazy resolution is
    what lets the bench sweep tiles in-process (rebuild the train step
    under a different env value -> fresh trace picks it up). The upper
    bound keeps the three f32 VMEM scratch tiles + operand tiles well
    inside the ~16 MB/core VMEM budget instead of failing later with an
    opaque Mosaic allocation error."""
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return default
    try:
        v = int(raw)
    except ValueError:
        raise ValueError(
            f"env {name}={raw!r} is not an integer; unset it or use a "
            "positive multiple of 128"
        ) from None
    if v <= 0 or v % 128 or v > 1024:
        raise ValueError(
            f"env {name}={v} must be a positive multiple of 128 and at most "
            "1024 (VMEM residency: scratch + operand tiles must fit in "
            "~16 MB/core)"
        )
    return v


# MXU/VPU tiles: sublane multiple of 8 (f32) / 16 (bf16), lane multiple
# of 128. Defaults are SEQUENCE-ADAPTIVE, fit to the on-chip sweeps
# (TPUCHECK.json round 5): at seq 1024 the best shape was (256, 512) —
# 31.5-33% MFU vs 25.8% at (128,128) — and at seq 4096 deeper tiles
# (512, 1024) beat (256, 512) by another ~16% tokens/s; bigger k tiles
# amortize the per-tile online-softmax rescale (VPU work the MXU waits
# on) and longer q tiles pay off once the sequence is long enough to
# fill them. Setting JOBSET_TPU_FLASH_TILE_Q/K pins a shape absolutely
# (still clamped to the padded sequence so short shapes never over-pad).
def _tile_q(tq_p: int) -> int:
    env = _tile_env("JOBSET_TPU_FLASH_TILE_Q", 0)
    if env:
        return env
    # Floor to a 128 multiple: the lane/sublane tiling rule the env path
    # validates must hold for computed tiles too (tq_p//8 is only a
    # 128-multiple when tq_p is a 1024-multiple).
    return min(1024, max(256, (tq_p // 8) // 128 * 128))


def _tile_k(tk_p: int) -> int:
    env = _tile_env("JOBSET_TPU_FLASH_TILE_K", 0)
    if env:
        return env
    return min(1024, max(512, (tk_p // 4) // 128 * 128))


_LANE = 128

_INTERPRET = False


@contextlib.contextmanager
def force_interpret():
    """Run the Pallas kernel via the interpreter (CPU tests).

    Trace-time flag: it is baked into any executable traced while the
    context is active, and a jit cache populated outside it will NOT
    re-trace inside it (and vice versa). Build the jitted callables you
    want interpreted *inside* the context — test-only helper."""
    global _INTERPRET
    prev, _INTERPRET = _INTERPRET, True
    try:
        yield
    finally:
        _INTERPRET = prev


def _use_pallas() -> bool:
    # Evaluated at trace time: set JOBSET_TPU_NO_PALLAS (escape hatch /
    # debugging) before building jitted steps; cached executables keep
    # whichever path they were traced with.
    if os.environ.get("JOBSET_TPU_NO_PALLAS"):
        return False
    return _INTERPRET or jax.default_backend() == "tpu"


# ---------------------------------------------------------------------------
# jnp reference (also the recompute path for the backward pass)
# ---------------------------------------------------------------------------


def _block_probs(q, k, bias):
    """Shared logits -> masked unnormalized-probabilities pipeline: the ONE
    definition of the block's softmax numerator, used by the forward
    reference AND re-run by the hand-written backward's recompute — any
    edit to masking/scaling here stays fwd/bwd-consistent by construction.

    Matmul operands stay in the INPUT dtype (bf16 from the training path —
    MXU rate; f32 in the differential tests) with f32 accumulation via
    `preferred_element_type`; statistics are always f32.
    Returns (block_max [B,H,Tq] f32, probs [B,H,Tq,Tk] f32).
    """
    scale = q.shape[-1] ** -0.5
    logits = (
        jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32)
        * scale
    )
    logits = logits + bias[None, None, :, :].astype(jnp.float32)
    block_max = jnp.max(logits, axis=-1)  # [B,H,Tq]
    probs = jnp.exp(logits - block_max[..., None])
    # Fully-masked rows: exp(-inf - -inf)=exp(0)=1 would pollute; zero them.
    valid = block_max > NEG_INF / 2
    probs = jnp.where(valid[..., None], probs, 0.0)
    return block_max, probs


def block_attention_reference(q, k, v, bias):
    """One flash step in plain jnp.

    q: [B, Tq, H, D], k/v: [B, Tk, H, D], bias: [Tq, Tk] additive mask.
    Returns (block_max [B,H,Tq], block_sum [B,H,Tq], weighted [B,Tq,H,D]).
    Dtype policy: see `_block_probs`.
    """
    block_max, probs = _block_probs(q, k, bias)
    block_sum = jnp.sum(probs, axis=-1)  # [B,H,Tq]
    weighted = jnp.einsum(
        "bhqk,bkhd->bqhd", probs.astype(v.dtype), v,
        preferred_element_type=jnp.float32,
    )
    return block_max, block_sum, weighted


# ---------------------------------------------------------------------------
# Pallas kernel
# ---------------------------------------------------------------------------


def _flash_block_kernel(
    q_ref, k_ref, v_ref, bias_ref, stats_ref, out_ref,
    m_scr, l_scr, acc_scr, *, scale,
):
    """Grid cell = (bh, q_tile, kv_tile); the kv axis is minor-most, so TPU
    executes kv tiles sequentially per q tile and K/V stream through VMEM
    one (TILE_K, Dp) block at a time — long contexts never hold the full
    K/V (or bias row) resident. Blocks:

    q_ref   [1, TILE_Q, Dp]      one q tile of one (batch, head)
    k_ref   [1, TILE_K, Dp]      one kv tile of that (batch, head)
    v_ref   [1, TILE_K, Dp]
    bias_ref[TILE_Q, TILE_K]
    stats_ref[1, TILE_Q, 8]      m_i in lanes 0:4, l_i in lanes 4:8
    out_ref [1, TILE_Q, Dp]      final weighted values (unnormalized)

    The running max m_i and unnormalized sum l_i are packed into ONE
    narrow output (the caller reads columns 0 and 4): TPU lowering
    requires the last two dims of every block to be (8k, 128m)-tiled OR
    equal to the full array dims, so a [1, TILE_Q] 2-D block — whose
    sublane dim is 1 — is rejected by the real lowering (the interpreter
    accepts it), while a full [TILE_Q, 128] lane-broadcast block per stat
    would write 128x the useful bytes to HBM. An 8-lane last dim equal to
    the array's last dim satisfies the tiling rule at 1/16th the traffic.

    The online-softmax accumulator lives in VMEM scratch, which persists
    across grid steps of the same (bh, q_tile).
    """
    kt = pl.program_id(2)

    @pl.when(kt == 0)
    def _init():
        # Stats scratch is lane-width (TQ, 128) for tile alignment; the
        # value lives broadcast across lanes, column 0 is read back.
        m_scr[:] = jnp.full(m_scr.shape, NEG_INF, jnp.float32)
        l_scr[:] = jnp.zeros(l_scr.shape, jnp.float32)
        acc_scr[:] = jnp.zeros(acc_scr.shape, jnp.float32)

    # Operands stay in their storage dtype (bf16 on the training path) so
    # the MXU runs at bf16 rate; accumulation and everything after the
    # matmul is f32. The scale is applied to the f32 logits, not the
    # (possibly bf16) q, so no precision is lost to the pre-scaling.
    q = q_ref[0]  # [TQ, Dp]
    k_t = k_ref[0]  # [TK, Dp]
    v_t = v_ref[0]
    b_t = bias_ref[:].astype(jnp.float32)  # [TQ, TK]

    logits = (
        lax.dot_general(
            q, k_t, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        * scale
        + b_t
    )  # [TQ, TK]

    m = m_scr[:, 0:1]
    new_m = jnp.maximum(m, jnp.max(logits, axis=-1, keepdims=True))
    p = jnp.exp(logits - new_m)
    # Masked-out entries (bias NEG_INF) must not contribute even when the
    # whole row is masked (new_m == NEG_INF would make exp(0) == 1).
    p = jnp.where(logits > NEG_INF / 2, p, 0.0)
    correction = jnp.exp(m - new_m)
    new_l = l_scr[:, 0:1] * correction + jnp.sum(p, axis=-1, keepdims=True)
    m_scr[:] = jnp.broadcast_to(new_m, m_scr.shape)
    l_scr[:] = jnp.broadcast_to(new_l, l_scr.shape)
    acc_scr[:] = acc_scr[:] * correction + lax.dot_general(
        p.astype(v_t.dtype), v_t, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )

    @pl.when(kt == pl.num_programs(2) - 1)
    def _finalize():
        stats_ref[0] = jnp.concatenate(
            [m_scr[:, 0:4], l_scr[:, 0:4]], axis=1
        )
        out_ref[0] = acc_scr[:]


def _pad_to(x, size, axis, value=0.0):
    pad = size - x.shape[axis]
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths, constant_values=value)


def _round_up(n, m):
    return -(-n // m) * m


def _block_attention_pallas(q, k, v, bias):
    """Pad to TPU tiles, run the kernel over a (B*H, q_tiles) grid, unpad."""
    batch, tq, heads, dim = q.shape
    tk = k.shape[1]
    scale = dim ** -0.5

    # Adaptive tile selection against the 128-padded sequence, clamped so
    # short sequences (decode prefill, ragged tests) never over-pad.
    tq_128 = _round_up(tq, 128)
    tk_128 = _round_up(tk, 128)
    tile_q = min(_tile_q(tq_128), tq_128)
    tile_k = min(_tile_k(tk_128), tk_128)
    tq_p = _round_up(tq, tile_q)
    tk_p = _round_up(tk, tile_k)
    d_p = _round_up(dim, _LANE)

    # Layout: [B, T, H, D] -> [B*H, T_pad, D_pad]; padded kv columns are
    # killed via NEG_INF bias, padded q rows are sliced off afterwards.
    def to_bh(x, t_p):
        x = jnp.moveaxis(x, 2, 1).reshape(batch * heads, x.shape[1], dim)
        return _pad_to(_pad_to(x, t_p, axis=1), d_p, axis=2)

    qp, kp, vp = to_bh(q, tq_p), to_bh(k, tk_p), to_bh(v, tk_p)
    bias_p = _pad_to(
        _pad_to(bias.astype(jnp.float32), tk_p, axis=1, value=NEG_INF),
        tq_p, axis=0,
    )

    grid = (batch * heads, tq_p // tile_q, tk_p // tile_k)

    # Inside shard_map the outputs vary over every axis any input varies
    # over (shard_map's check_vma requires out_shape to declare this), and
    # every operand must agree — promote the laggards up to the union.
    from ..parallel.mesh import pvary_to, vma_union

    vma = vma_union(q, k, v, bias)
    qp, kp, vp, bias_p = (pvary_to(x, vma) for x in (qp, kp, vp, bias_p))

    def out_struct(shape):
        return jax.ShapeDtypeStruct(shape, jnp.float32, vma=vma)

    from jax.experimental.pallas import tpu as pltpu

    kernel = functools.partial(_flash_block_kernel, scale=scale)
    stats, weighted = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, tile_q, d_p), lambda bh, qi, kt: (bh, qi, 0)),
            pl.BlockSpec((1, tile_k, d_p), lambda bh, qi, kt: (bh, kt, 0)),
            pl.BlockSpec((1, tile_k, d_p), lambda bh, qi, kt: (bh, kt, 0)),
            pl.BlockSpec((tile_q, tile_k), lambda bh, qi, kt: (qi, kt)),
        ],
        out_specs=[
            pl.BlockSpec((1, tile_q, 8), lambda bh, qi, kt: (bh, qi, 0)),
            pl.BlockSpec((1, tile_q, d_p), lambda bh, qi, kt: (bh, qi, 0)),
        ],
        out_shape=[
            out_struct((batch * heads, tq_p, 8)),
            out_struct((batch * heads, tq_p, d_p)),
        ],
        scratch_shapes=[
            pltpu.VMEM((tile_q, _LANE), jnp.float32),
            pltpu.VMEM((tile_q, _LANE), jnp.float32),
            pltpu.VMEM((tile_q, d_p), jnp.float32),
        ],
        interpret=_INTERPRET,
    )(qp, kp, vp, bias_p)

    block_max = stats[:, :, 0].reshape(batch, heads, tq_p)[:, :, :tq]
    block_sum = stats[:, :, 4].reshape(batch, heads, tq_p)[:, :, :tq]
    weighted = weighted.reshape(batch, heads, tq_p, d_p)[:, :, :tq, :dim]
    weighted = jnp.moveaxis(weighted, 1, 2)  # [B, Tq, H, D]
    return block_max, block_sum, weighted


def _repeat_heads(x, group: int):
    """GQA broadcast [B, T, Hkv, D] -> [B, T, Hkv*group, D]; fuses into the
    consuming matmul (broadcast+reshape, never a copy)."""
    if group == 1:
        return x
    b, t, hkv, d = x.shape
    return jnp.broadcast_to(
        x[:, :, :, None, :], (b, t, hkv, group, d)
    ).reshape(b, t, hkv * group, d)


def merge_block_stats(acc, blk):
    """Online-softmax merge of two unnormalized (max, sum, weighted) triples
    — THE recurrence both sequence-parallel strategies fold with
    (ring_attention per ppermute step, ulysses_attention per local chunk),
    shared so their numerics cannot drift apart.

    max/sum are [B, H, Tq]; weighted is [B, Tq, H, D].
    """
    acc_max, acc_sum, acc_out = acc
    blk_max, blk_sum, blk_out = blk
    new_max = jnp.maximum(acc_max, blk_max)
    old_scale = jnp.exp(acc_max - new_max)
    blk_scale = jnp.exp(blk_max - new_max)
    new_sum = acc_sum * old_scale + blk_sum * blk_scale
    new_out = (
        acc_out * old_scale.transpose(0, 2, 1)[..., None]
        + blk_out * blk_scale.transpose(0, 2, 1)[..., None]
    )
    return new_max, new_sum, new_out


def normalize_block_stats(acc_sum, acc_out):
    """Final division of the folded accumulator; clamped so fully-masked
    rows yield 0 instead of NaN."""
    denom = jnp.maximum(acc_sum, 1e-20).transpose(0, 2, 1)[..., None]
    return acc_out / denom


def blockwise_causal_attention(q, k, v, chunk: int = 512, causal: bool = True):
    """Exact attention over contiguous positions, folded blockwise so no
    [T, T] bias or probability matrix ever materializes: biases are
    per-chunk-pair constants ([c, c] triangular on the diagonal, zero
    elsewhere), and with `causal` strictly-future chunk pairs are skipped.
    Collective-free — the local building block both `ulysses_attention`
    (after its gather) and the serving prefill fold with.

    q/k/v: [B, T, H, D] covering positions 0..T-1. k/v may carry FEWER
    heads than q (GQA): each group of H_q/H_kv query heads shares one K/V
    head, broadcast per block inside the fold — callers ship/hold only the
    compact K/V. The final chunk may be ragged; all shapes are static at
    trace time.

    The Python loops unroll O(n_chunks^2) kernel calls into the trace, so
    the chunk is floored at T/16: at most ~136 kernel calls regardless of
    sequence length, with per-block bias/scratch of (T/16)^2 — 256x
    smaller than the [T, T] materialization this fold avoids, though still
    quadratic in T. (A scan-folded inner loop would make truly-long-prompt
    memory linear at fixed chunk; at the sequence lengths served today the
    T/16 tile is the better compile-time/memory trade.)
    """
    t_total = q.shape[1]
    batch, _, heads, dim = q.shape
    group = heads // k.shape[2]
    chunk = max(chunk, -(-t_total // 16))
    starts = list(range(0, t_total, chunk))

    def tri(n):
        rel = jnp.arange(n)[:, None] - jnp.arange(n)[None, :]
        return jnp.where(rel >= 0, 0.0, NEG_INF).astype(jnp.float32)

    out_chunks = []
    for i, qs in enumerate(starts):
        q_len = min(chunk, t_total - qs)
        q_i = lax.slice_in_dim(q, qs, qs + q_len, axis=1)
        acc = (
            jnp.full((batch, heads, q_len), NEG_INF, jnp.float32),
            jnp.zeros((batch, heads, q_len), jnp.float32),
            jnp.zeros((batch, q_len, heads, dim), jnp.float32),
        )
        kv_starts = starts[: i + 1] if causal else starts
        for j, ks in enumerate(kv_starts):
            k_len = min(chunk, t_total - ks)
            if causal and j == i:
                bias = tri(q_len)
            else:
                bias = jnp.zeros((q_len, k_len), jnp.float32)
            blk = block_attention(
                q_i,
                _repeat_heads(lax.slice_in_dim(k, ks, ks + k_len, axis=1), group),
                _repeat_heads(lax.slice_in_dim(v, ks, ks + k_len, axis=1), group),
                bias,
            )
            acc = merge_block_stats(acc, blk)
        out_chunks.append(normalize_block_stats(acc[1], acc[2]))
    return jnp.concatenate(out_chunks, axis=1)


# ---------------------------------------------------------------------------
# Public op with flash-style recompute backward
# ---------------------------------------------------------------------------


@jax.custom_vjp
def block_attention(q, k, v, bias):
    """Dispatching flash block step; see module docstring for the contract.

    q/k/v stay in their incoming dtype — the matmuls run at the MXU's
    native rate for that dtype (bf16 on the training path) and accumulate
    in f32; bias and the softmax statistics are always f32, so both
    dispatch paths return identical f32 outputs regardless of backend."""
    bias = bias.astype(jnp.float32)
    if _use_pallas():
        return _block_attention_pallas(q, k, v, bias)
    return block_attention_reference(q, k, v, bias)


def _fwd(q, k, v, bias):
    return block_attention(q, k, v, bias), (q, k, v, bias)


def _bwd(residuals, cotangents):
    """Hand-written flash recompute backward.

    Recomputes the block's logits/probabilities (never stored — the
    standard flash strategy) and forms the five backward matmuls with
    operands cast to the inputs' compute dtype, f32-accumulated: the f32
    jax.vjp this replaces ran every backward matmul at the MXU's (much
    slower) f32 rate, which taxed the hot op's backward ~3x.

    The block max is treated as a constant of the recompute (no cotangent
    flows through the max): the (max, sum, weighted) triple is a gauge —
    every downstream consumer (`merge_block_stats` + normalization) is
    invariant to shifting max by d while scaling sum/weighted by exp(-d) —
    so the end-to-end gradient is independent of the representative, which
    is exactly why flash backwards never differentiate the max. Verified
    against dense-attention autodiff in tests/test_ops.py.
    """
    q, k, v, bias = residuals
    dmax, dsum, dweighted = cotangents
    compute = q.dtype
    scale = q.shape[-1] ** -0.5

    # Recompute this block's unnormalized probabilities — the same
    # `_block_probs` the forward ran, so fwd/bwd cannot drift.
    _, probs = _block_probs(q, k, bias)

    # d(probs): from block_sum (broadcast) and from weighted = probs @ v.
    dw_c = dweighted.astype(compute)
    dprobs = dsum[..., None] + jnp.einsum(
        "bqhd,bkhd->bhqk", dw_c, v, preferred_element_type=jnp.float32
    )
    # Unnormalized probs: d(logits) = probs * d(probs) — no softmax-Jacobian
    # subtraction here; downstream normalization delivers it via `dsum`.
    dlogits = probs * dprobs
    dl_c = dlogits.astype(compute)
    probs_c = probs.astype(compute)

    dq = jnp.einsum(
        "bhqk,bkhd->bqhd", dl_c, k, preferred_element_type=jnp.float32
    ) * scale
    dk = jnp.einsum(
        "bhqk,bqhd->bkhd", dl_c, q, preferred_element_type=jnp.float32
    ) * scale
    dv = jnp.einsum(
        "bhqk,bqhd->bkhd", probs_c, dw_c, preferred_element_type=jnp.float32
    )
    dbias = jnp.sum(dlogits, axis=(0, 1))
    del dmax  # gauge direction: no flow through the block max

    def match_input(g, x):
        """shard_map VMA typing: a cotangent must vary over exactly the
        axes its primal input does. An input invariant over an axis the
        cotangent varies over (the constant causal bias inside a dp x sp
        shard_map, say) takes the psum over those axes — the transpose of
        the pvary the forward inserted, i.e. the true replicated-input
        gradient. (jax.vjp inserted these automatically for the old
        recompute; a hand-written bwd states them explicitly.)"""
        extra = tuple(
            getattr(jax.typeof(g), "vma", frozenset())
            - getattr(jax.typeof(x), "vma", frozenset())
        )
        if extra:
            g = lax.psum(g, extra)
        return g.astype(x.dtype)

    return (
        match_input(dq, q),
        match_input(dk, k),
        match_input(dv, v),
        match_input(dbias, bias),
    )


block_attention.defvjp(_fwd, _bwd)
