"""TPU kernels for the workload plane's hot ops (Pallas).

The reference framework contains no numerical code (SURVEY.md §2 — JobSet
is a job orchestrator); these kernels are the greenfield TPU-native compute
the orchestrated workloads actually run.
"""

from .flash_block import (  # noqa: F401
    NEG_INF,
    block_attention,
    block_attention_reference,
    force_interpret,
)
