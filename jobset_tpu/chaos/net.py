"""Per-link network fault model: the chaos plane's partition engine.

The injector's existing points model *process* faults (a request errors,
a stream breaks, a pod crashes); this module models the *network* — the
failure class quorum replication actually exists for. A
:class:`PartitionPlan` is a deterministic, seeded schedule of **directed
link transitions**: ``cut(src, dst)`` makes every delivery from ``src``
to ``dst`` fail (blackhole/refuse) until a scheduled ``heal``. Because
links are directed, asymmetric partitions (A can reach B, B cannot reach
A) and flapping links are first-class.

Design constraints (the same three the injector carries):

1. **Deterministic.** The plan is a pure function of (seed, the
   schedule the scenario declared, the logical step at which
   ``advance()`` is called). Per-delivery checks draw NO randomness and
   append NO log entries — only scheduled cut/heal *transitions* are
   recorded (via ``FaultInjector.record``) — so timing-dependent arrival
   counts (read-fence probes, client retries) cannot perturb the
   injection log, and two seeded runs stay byte-identical. Flap
   interval jitter comes from a ``random.Random`` seeded per link at
   schedule-build time.
2. **Near-zero cost when off.** ``check_link`` returns immediately when
   no injector/plan is configured; transports guard with one call.
3. **Observable.** Cut AND heal transitions land in the injection log
   as first-class ``net.partition`` entries (heals included — recovery
   timing is part of the seeded contract), and blocked deliveries bump
   ``jobset_chaos_partition_blocked_total`` per link.

Time is a **logical step counter**, never the wall clock: the scenario
driver calls ``plan.advance(step)`` between storm iterations (step =
write index), so scheduled heals replay byte-identically. Callers that
want immediate effect (bench wall-clock windows) use ``apply_cut`` /
``apply_heal``, which schedule at the current step and advance in place.

Enforcement sits at both transports: ``ha/replication.py`` (LocalPeer
and HttpPeer consult ``guard()`` before every peer RPC, so a cut link
refuses instead of delivering append-entries/position/log/snapshot) and
``client.py`` (every client HTTP round trip consults ``check_link``
against the server's netloc). Rate-based rules at the ``net.partition``
point (CLI spec, e.g. ``net.partition:refuse@0.05``) ride the same
check and fire per delivery like any other injector rule.
"""

from __future__ import annotations

import random
import threading
from typing import Optional

from .injector import FaultInjector, consult, get_injector

KIND_CUT = "cut"
KIND_HEAL = "heal"

_POINT = "net.partition"


class PartitionPlan:
    """Seeded schedule of directed link cuts and heals.

    The schedule is a list of ``(step, kind, src, dst)`` transitions,
    applied in (step, insertion) order by :meth:`advance`. Scenarios
    build it up front (or extend it mid-run at deterministic steps —
    e.g. "cut whoever is leading at step 6", which is itself a
    deterministic identity in a seeded run)."""

    def __init__(self, seed: int = 0, injector: Optional[FaultInjector] = None):
        self.seed = seed
        self.injector = injector
        self._lock = threading.Lock()
        # PENDING transitions (insertion order): (step, kind, src, dst).
        # advance() consumes the due prefix in (step, insertion) order.
        self._schedule: list[tuple[int, str, str, str]] = []
        self._cut: set[tuple[str, str]] = set()
        self.step = 0
        # (src, dst) -> deliveries blocked while the link was cut
        # (counters only — per-delivery log entries would make the log
        # timing-dependent; see module docstring).
        self.blocked: dict[tuple[str, str], int] = {}
        if injector is not None:
            # The transports resolve the plan through the injector they
            # already carry, so scenario wiring stays one object.
            injector.partition_plan = self

    # -- schedule building --------------------------------------------------

    def _links(self, src: str, dst: str, symmetric: bool):
        yield (src, dst)
        if symmetric:
            yield (dst, src)

    def cut(self, src: str, dst: str, at: int = 0,
            heal_at: Optional[int] = None, symmetric: bool = False) -> None:
        """Schedule a cut of src->dst at step `at` (and dst->src too when
        `symmetric`), healing at step `heal_at` (None = until healed
        explicitly)."""
        with self._lock:
            for a, b in self._links(src, dst, symmetric):
                self._schedule.append((int(at), KIND_CUT, a, b))
                if heal_at is not None:
                    self._schedule.append((int(heal_at), KIND_HEAL, a, b))

    def heal(self, src: str, dst: str, at: int = 0,
             symmetric: bool = False) -> None:
        """Schedule a heal of src->dst at step `at`."""
        with self._lock:
            for a, b in self._links(src, dst, symmetric):
                self._schedule.append((int(at), KIND_HEAL, a, b))

    def flap(self, src: str, dst: str, at: int, until: int,
             period: int = 2, symmetric: bool = False) -> int:
        """Schedule a flapping link: alternating cut/heal transitions from
        step `at` to step `until`, each interval `period` steps long with
        ±1 step of jitter drawn from a per-link seeded stream (so two
        flapping links don't move in lockstep). Always ends with a heal
        at `until`. Returns the number of transitions scheduled."""
        rng = random.Random(f"{self.seed}/{src}->{dst}")
        scheduled = 0
        step, kind = int(at), KIND_CUT
        with self._lock:
            while step < int(until):
                for a, b in self._links(src, dst, symmetric):
                    self._schedule.append((step, kind, a, b))
                    scheduled += 1
                kind = KIND_HEAL if kind == KIND_CUT else KIND_CUT
                step += max(1, period + rng.choice((-1, 0, 1)))
            for a, b in self._links(src, dst, symmetric):
                self._schedule.append((int(until), KIND_HEAL, a, b))
                scheduled += 1
        return scheduled

    # -- applying transitions ----------------------------------------------

    def advance(self, step: Optional[int] = None) -> list[dict]:
        """Apply every not-yet-applied scheduled transition with
        transition-step <= `step` (default: everything scheduled so far),
        in (step, insertion) order. Cut/heal events are recorded into the
        injector log as first-class entries. Returns the applied
        transitions."""
        applied: list[dict] = []
        with self._lock:
            if step is not None:
                self.step = max(self.step, int(step))
            target = self.step if step is not None else None
            indexed = list(enumerate(self._schedule))
            due = sorted(
                (
                    (at, i, kind, src, dst)
                    for i, (at, kind, src, dst) in indexed
                    if target is None or at <= target
                ),
                key=lambda t: (t[0], t[1]),
            )
            due_indexes = {i for _, i, _, _, _ in due}
            self._schedule = [
                t for i, t in indexed if i not in due_indexes
            ]
            for at, _i, kind, src, dst in due:
                link = (src, dst)
                if kind == KIND_CUT and link not in self._cut:
                    self._cut.add(link)
                    applied.append({
                        "step": at, "kind": KIND_CUT, "src": src, "dst": dst,
                    })
                elif kind == KIND_HEAL and link in self._cut:
                    self._cut.discard(link)
                    applied.append({
                        "step": at, "kind": KIND_HEAL, "src": src, "dst": dst,
                    })
        # Log OUTSIDE the plan lock (the injector takes its own).
        if self.injector is not None:
            for t in applied:
                self.injector.record(
                    _POINT, t["kind"],
                    f"{t['src']}->{t['dst']} @step {t['step']}",
                )
        return applied

    def _current_step(self) -> int:
        """Locked read of the plan clock — advance() writes it under the
        lock, and the apply-now helpers run on scenario/bench driver
        threads concurrent with delivery-path advances."""
        with self._lock:
            return self.step

    def apply_cut(self, src: str, dst: str, symmetric: bool = False) -> None:
        """Cut now (wall-clock callers: bench windows)."""
        step = self._current_step()
        self.cut(src, dst, at=step, symmetric=symmetric)
        self.advance(step)

    def isolate(self, node: str, others, at: Optional[int] = None) -> None:
        """Cut every link between `node` and each of `others`, both
        directions, at step `at` (default: now) and apply — THE
        leader-isolation fault, shared by the checker-gated scenarios
        and `bench.py --partition` so both measure the same cut."""
        step = self._current_step() if at is None else int(at)
        for other in others:
            if other != node:
                self.cut(node, other, at=step, symmetric=True)
        self.advance(step)

    def apply_heal(self, src: str, dst: str, symmetric: bool = False) -> None:
        """Heal now (wall-clock callers: bench windows)."""
        step = self._current_step()
        self.heal(src, dst, at=step, symmetric=symmetric)
        self.advance(step)

    def heal_all(self, step: Optional[int] = None) -> list[dict]:
        """Schedule-and-apply a heal of every currently-cut link (scenario
        teardown / convergence phase)."""
        with self._lock:
            cut = sorted(self._cut)
            at = self.step if step is None else int(step)
        for src, dst in cut:
            self.heal(src, dst, at=at)
        return self.advance(at)

    # -- per-delivery checks ------------------------------------------------

    def is_cut(self, src: str, dst: str) -> bool:
        with self._lock:
            return (src, dst) in self._cut

    def note_blocked(self, src: str, dst: str) -> None:
        with self._lock:
            self.blocked[(src, dst)] = self.blocked.get((src, dst), 0) + 1
        from ..core import metrics

        metrics.chaos_partition_blocked_total.inc(f"{src}->{dst}")

    def cut_links(self) -> list[tuple[str, str]]:
        with self._lock:
            return sorted(self._cut)


def get_plan(injector: Optional[FaultInjector] = None) -> Optional[PartitionPlan]:
    """Resolve the active plan: the one attached to `injector` (explicit,
    else the process-global injector the CLI's ``--inject`` installs).
    There is deliberately no plan-only global: a plan without an injector
    could not log its transitions, and every install path — supervisor,
    scenarios, bench, an embedding process — already owns an injector to
    attach to. CLI-only deployments reach this point through rate rules
    (``net.partition:refuse@RATE``), which need no plan at all."""
    if injector is None:
        injector = get_injector()
    return getattr(injector, "partition_plan", None) if injector else None


def check_link(src: str, dst: str,
               injector: Optional[FaultInjector] = None) -> Optional[str]:
    """One delivery over the directed link src->dst: returns a reason
    string when the delivery must fail (link cut by the active plan, or a
    rate-based ``net.partition`` rule fired), else None. Shared by both
    transports and the client so partition semantics cannot drift."""
    if injector is None:
        injector = get_injector()
    fault = consult(_POINT, f"{src}->{dst}", injector=injector)
    if fault is not None:
        return (
            f"chaos {_POINT}: injected {fault.kind} on link "
            f"{src}->{dst} (seq {fault.seq})"
        )
    plan = get_plan(injector)
    if plan is not None and plan.is_cut(src, dst):
        plan.note_blocked(src, dst)
        return f"chaos {_POINT}: link {src}->{dst} is cut"
    return None


def guard(src: str, dst: str,
          injector: Optional[FaultInjector] = None) -> None:
    """check_link that raises ConnectionError — what the HA peer
    transports call before dialing (a cut link refuses instead of
    delivering)."""
    reason = check_link(src, dst, injector=injector)
    if reason is not None:
        raise ConnectionError(reason)


__all__ = [
    "KIND_CUT",
    "KIND_HEAL",
    "PartitionPlan",
    "check_link",
    "get_plan",
    "guard",
]
