"""Cluster-side chaos scenarios: deterministic pod crash bursts and node
drains driven through the simulation kernel's own fault helpers.

These are the `cluster.*` injection points of the chaos plane. Unlike the
apiserver/solver points — which sit inline on real request paths — cluster
faults are *applied* by calling one of these helpers between pump rounds,
the way the failure-recovery bench applies `fail_node`. The injector still
owns every random choice (which pods crash, which node drains), so a
seeded run selects identical victims every time.
"""

from __future__ import annotations

from typing import Optional

from .injector import FaultInjector, KIND_CRASH, KIND_DRAIN, KIND_EVICT

# Pod phases considered "live" for victim selection (mirrors
# core/objects.py constants without importing the whole core package at
# module load).
_LIVE_PHASES = ("Pending", "Running")


def pod_crash_burst(
    cluster,
    injector: FaultInjector,
    rate: Optional[float] = None,
    detail: str = "",
) -> list[str]:
    """Crash a deterministic subset of live pods (container-crash analog).

    Every live pod is one arrival at the ``cluster.pod`` point, visited in
    sorted (namespace, name) order so the victim set is a pure function of
    the seed and the pod population. With ``rate`` given, a transient rule
    at that rate is installed for exactly this sweep; otherwise whatever
    ``cluster.pod`` rules the injector already carries decide.

    Returns the crashed pod names. The owning jobs observe the failures on
    the next pump round exactly like real crashes (backoffLimit accounting,
    failure policy, gang restart).
    """
    rule = None
    if rate is not None:
        rule = injector.add_rule("cluster.pod", KIND_CRASH, rate=rate)
    crashed: list[str] = []
    try:
        for key in sorted(cluster.pods):
            pod = cluster.pods.get(key)
            if pod is None or pod.status.phase not in _LIVE_PHASES:
                continue
            fault = injector.check(
                "cluster.pod", detail or f"{key[0]}/{key[1]}"
            )
            if fault is not None and fault.kind == KIND_CRASH:
                cluster.fail_pod(*key)
                crashed.append(key[1])
    finally:
        if rule is not None:
            injector.remove_rule(rule)
    return crashed


def node_drain(
    cluster,
    injector: FaultInjector,
    rate: Optional[float] = None,
) -> list[str]:
    """Drain a deterministic subset of nodes (maintenance-event analog).

    Each node is one arrival at ``cluster.node`` in sorted-name order;
    a drained node fails every live pod bound to it via the kernel's
    `fail_node` (jobs get Failed conditions -> failure policy -> gang
    recovery). Returns the drained node names.
    """
    rule = None
    if rate is not None:
        rule = injector.add_rule("cluster.node", KIND_DRAIN, rate=rate)
    drained: list[str] = []
    try:
        for name in sorted(cluster.nodes):
            fault = injector.check("cluster.node", name)
            if fault is not None and fault.kind == KIND_DRAIN:
                cluster.fail_node(name)
                drained.append(name)
    finally:
        if rule is not None:
            injector.remove_rule(rule)
    return drained


def queue_spurious_evictions(
    cluster,
    injector: FaultInjector,
    rate: Optional[float] = None,
) -> list[str]:
    """Spuriously evict a deterministic subset of admitted gangs
    (maintenance-preemption / quota-revocation analog).

    Each admitted workload of the cluster's `QueueManager` is one arrival
    at the ``queue.admission`` point, visited in sorted (namespace, name)
    order; an ``evict`` fault re-suspends the gang and requeues it with
    backoff through the manager's own eviction path — so recovery
    (re-admission when eligible, Kueue-mutable merge on re-resume) is
    exercised exactly as a real preemption would. Returns the evicted
    JobSet names.
    """
    manager = getattr(cluster, "queue_manager", None)
    if manager is None:
        return []
    rule = None
    if rate is not None:
        rule = injector.add_rule("queue.admission", KIND_EVICT, rate=rate)
    evicted: list[str] = []
    try:
        admitted = sorted(
            (wl for wl in manager.workloads.values()
             if wl.state == "Admitted"),
            key=lambda wl: wl.key,
        )
        for wl in admitted:
            fault = injector.check(
                "queue.admission", f"{wl.key[0]}/{wl.key[1]}"
            )
            if fault is not None and fault.kind == KIND_EVICT:
                if manager.evict(
                    wl.uid, message="chaos: spurious eviction"
                ):
                    evicted.append(wl.key[1])
    finally:
        if rule is not None:
            injector.remove_rule(rule)
    return evicted
