"""Cluster-side chaos scenarios: deterministic pod crash bursts and node
drains driven through the simulation kernel's own fault helpers.

These are the `cluster.*` injection points of the chaos plane. Unlike the
apiserver/solver points — which sit inline on real request paths — cluster
faults are *applied* by calling one of these helpers between pump rounds,
the way the failure-recovery bench applies `fail_node`. The injector still
owns every random choice (which pods crash, which node drains), so a
seeded run selects identical victims every time.
"""

from __future__ import annotations

from typing import Optional

from .injector import (
    FaultInjector,
    KIND_CORRUPT,
    KIND_CRASH,
    KIND_DRAIN,
    KIND_ENOSPC,
    KIND_EVICT,
    KIND_TORN,
)

# Pod phases considered "live" for victim selection (mirrors
# core/objects.py constants without importing the whole core package at
# module load).
_LIVE_PHASES = ("Pending", "Running")


def _columnar_cluster(**kwargs):
    """Scenario cluster factory with the ColumnarCore gate ON — the
    docs/columnar.md graduation plan's step 2: the storm/chaos soaks run
    on the array-backed core first, where every scenario's seeded
    byte-identity assertion doubles as the columnar parity gate (the
    gate is sampled at Cluster construction, so it must wrap HERE, not
    at the scenario entry point — ReplicaSet promotions construct their
    clusters on later call stacks)."""
    from ..core import features, make_cluster

    with features.gate("ColumnarCore", True):
        return make_cluster(**kwargs)


def pod_crash_burst(
    cluster,
    injector: FaultInjector,
    rate: Optional[float] = None,
    detail: str = "",
) -> list[str]:
    """Crash a deterministic subset of live pods (container-crash analog).

    Every live pod is one arrival at the ``cluster.pod`` point, visited in
    sorted (namespace, name) order so the victim set is a pure function of
    the seed and the pod population. With ``rate`` given, a transient rule
    at that rate is installed for exactly this sweep; otherwise whatever
    ``cluster.pod`` rules the injector already carries decide.

    Returns the crashed pod names. The owning jobs observe the failures on
    the next pump round exactly like real crashes (backoffLimit accounting,
    failure policy, gang restart).
    """
    rule = None
    if rate is not None:
        rule = injector.add_rule("cluster.pod", KIND_CRASH, rate=rate)
    crashed: list[str] = []
    try:
        for key in sorted(cluster.pods):
            pod = cluster.pods.get(key)
            if pod is None or pod.status.phase not in _LIVE_PHASES:
                continue
            fault = injector.check(
                "cluster.pod", detail or f"{key[0]}/{key[1]}"
            )
            if fault is not None and fault.kind == KIND_CRASH:
                from ..api import keys as api_keys  # constants-only module

                owner = pod.labels.get(api_keys.JOBSET_NAME_KEY)
                cluster.fail_pod(*key)
                crashed.append(key[1])
                # First-class event on the owning JobSet so the injection
                # lands in its flight-recorder timeline at virtual-clock
                # time (the seq joins the injector's log).
                if owner:
                    cluster.record_event(
                        "JobSet", owner, "Warning", "ChaosPodCrash",
                        f"chaos: injected crash of pod {key[1]} "
                        f"(injection seq {fault.seq})",
                        namespace=key[0],
                    )
    finally:
        if rule is not None:
            injector.remove_rule(rule)
    return crashed


def node_drain(
    cluster,
    injector: FaultInjector,
    rate: Optional[float] = None,
) -> list[str]:
    """Drain a deterministic subset of nodes (maintenance-event analog).

    Each node is one arrival at ``cluster.node`` in sorted-name order;
    a drained node fails every live pod bound to it via the kernel's
    `fail_node` (jobs get Failed conditions -> failure policy -> gang
    recovery). Returns the drained node names.
    """
    rule = None
    if rate is not None:
        rule = injector.add_rule("cluster.node", KIND_DRAIN, rate=rate)
    drained: list[str] = []
    try:
        for name in sorted(cluster.nodes):
            fault = injector.check("cluster.node", name)
            if fault is not None and fault.kind == KIND_DRAIN:
                failed_jobs = cluster.fail_node(name)
                drained.append(name)
                # One event per drained node (kind Node, so it reaches the
                # events API / field selectors without attaching to any
                # single JobSet's timeline).
                cluster.record_event(
                    "Node", name, "Warning", "ChaosNodeDrain",
                    f"chaos: injected drain failed {len(failed_jobs)} "
                    f"job(s) (injection seq {fault.seq})",
                )
    finally:
        if rule is not None:
            injector.remove_rule(rule)
    return drained


def queue_spurious_evictions(
    cluster,
    injector: FaultInjector,
    rate: Optional[float] = None,
) -> list[str]:
    """Spuriously evict a deterministic subset of admitted gangs
    (maintenance-preemption / quota-revocation analog).

    Each admitted workload of the cluster's `QueueManager` is one arrival
    at the ``queue.admission`` point, visited in sorted (namespace, name)
    order; an ``evict`` fault re-suspends the gang and requeues it with
    backoff through the manager's own eviction path — so recovery
    (re-admission when eligible, Kueue-mutable merge on re-resume) is
    exercised exactly as a real preemption would. Returns the evicted
    JobSet names.
    """
    manager = getattr(cluster, "queue_manager", None)
    if manager is None:
        return []
    rule = None
    if rate is not None:
        rule = injector.add_rule("queue.admission", KIND_EVICT, rate=rate)
    evicted: list[str] = []
    try:
        admitted = sorted(
            (wl for wl in manager.workloads.values()
             if wl.state == "Admitted"),
            key=lambda wl: wl.key,
        )
        for wl in admitted:
            fault = injector.check(
                "queue.admission", f"{wl.key[0]}/{wl.key[1]}"
            )
            if fault is not None and fault.kind == KIND_EVICT:
                if manager.evict(
                    wl.uid, message="chaos: spurious eviction"
                ):
                    evicted.append(wl.key[1])
    finally:
        if rule is not None:
            injector.remove_rule(rule)
    return evicted


def store_torn_writes(
    data_dir: str,
    rates=(0.0, 0.1, 0.3, 0.6),
    seed: int = 11,
    writes: int = 24,
    kind: str = KIND_TORN,
) -> list[dict]:
    """Durable-store fault sweep at the ``store.write`` point: for each
    injection rate, drive a create/mutate/delete write sequence against a
    fresh cluster+store, committing after every write; a commit that hits
    an injected torn write (partial frame on disk, no fsync ack) or ENOSPC
    raises and is NOT acknowledged — the tail is repaired and the diff
    retries on the next commit, exactly as the server's commit path does.
    After the last write the store is hard-killed (abandoned, never closed
    or flushed) and recovered into a fresh cluster.

    The invariant each rate's result carries: every object covered by the
    last fsync-ACKNOWLEDGED commit is recovered byte-identically
    (``lost`` / ``mismatched`` are object counts — the caller asserts
    zero). Faults are deterministic per (seed, arrival), so a sweep is
    reproducible.
    """
    import os

    from ..store import Store, StoreError
    from ..testing import make_jobset, make_replicated_job

    results: list[dict] = []
    for i, rate in enumerate(rates):
        rate_dir = os.path.join(data_dir, f"{kind}-{i}")
        injector = FaultInjector(seed=seed)
        if rate > 0:
            injector.add_rule("store.write", kind, rate=rate)
        # Columnar core ON (docs/columnar.md graduation plan): recovery
        # byte-identity below is the parity assertion.
        cluster = _columnar_cluster()
        store = Store(rate_dir, snapshot_interval=10**9, injector=injector)
        store.recover(cluster)

        acked = failed = 0
        durable: dict = {}  # last fsync-acknowledged serialized state
        for w in range(writes):
            if w % 4 == 3:
                cluster.delete_jobset("default", f"wl-{w - 3}")
            else:
                cluster.create_jobset(
                    make_jobset(f"wl-{w}")
                    .replicated_job(
                        make_replicated_job("w").replicas(1)
                        .parallelism(1).completions(1).obj()
                    )
                    .suspend(True)
                    .obj()
                )
            cluster.run_until_stable()
            try:
                if store.commit() is not None:
                    acked += 1
                durable = store.serialized_state()
            except StoreError:
                failed += 1
                store.repair()

        # Hard-kill (no flush, no tail repair — per-record fsync is the
        # only durability), then cold-start recover.
        store.hard_kill()
        fresh = _columnar_cluster()
        recovered_store = Store(rate_dir)
        recovered_store.recover(fresh)
        recovered = recovered_store.serialized_state()
        recovered_store.close()

        lost = mismatched = 0
        for obj_kind, objs in durable.items():
            for key, serialized in objs.items():
                got = recovered.get(obj_kind, {}).get(key)
                if got is None:
                    lost += 1
                elif got != serialized:
                    mismatched += 1
        results.append({
            "kind": kind,
            "rate": rate,
            "writes": writes,
            "commits_acked": acked,
            "commits_failed": failed,
            "faults_injected": injector.injected_total("store.write"),
            "lost": lost,
            "mismatched": mismatched,
            "recovered_objects": sum(len(v) for v in recovered.values()),
        })
    return results


def store_enospc_writes(data_dir: str, **kwargs) -> list[dict]:
    """ENOSPC variant of `store_torn_writes` (append fails before any byte
    lands; the log needs no truncation but the commit is still unacked)."""
    kwargs.setdefault("kind", KIND_ENOSPC)
    return store_torn_writes(data_dir, **kwargs)


def policy_inference_faults(
    checkpoint_path: Optional[str],
    rates=(0.0, 0.25, 1.0),
    seed: int = 11,
    jobsets: int = 6,
    replicas: int = 2,
    pods_per_job: int = 2,
    domains: int = 8,
    nodes_per_domain: int = 2,
    kind: str = KIND_CORRUPT,
    crash_rate: float = 0.4,
    score_backend: str = "numpy",
) -> list[dict]:
    """Learned-placement fault sweep at the ``policy.inference`` point:
    for each injection rate, drive a fresh cluster with ACTIVE-mode
    `LearnedPlacement` (both placement gates on) through creation, a
    seeded pod-crash burst, and gang recovery, while every learned
    inference is one arrival at the point — a ``corrupt`` fault sends
    that gang to the auction solver fallback (counted: fallbacks ==
    faults). A ``latency`` fault only DELAYS the decision — consult()
    absorbs it — so latency sweeps keep decisions learned and bank
    ``fallbacks == 0``.

    The invariant each rate's result carries (the caller asserts):
    ``unplaced_gangs == 0`` and ``double_booked_domains == 0`` at EVERY
    rate — a sick model may cost optimality, never placement.
    """
    from ..core import features, make_cluster, metrics
    from ..policy.placer import LearnedPlacement
    from ..testing import make_jobset, make_replicated_job

    topology_key = "tpu-slice"
    results: list[dict] = []
    for i, rate in enumerate(rates):
        injector = FaultInjector(seed=seed)
        if rate > 0:
            injector.add_rule("policy.inference", kind, rate=rate)
        placement = LearnedPlacement(
            checkpoint_path=checkpoint_path,
            mode="active",
            injector=injector,
            score_backend=score_backend,
        )
        fallbacks0 = metrics.policy_fallbacks_total.total()
        decisions0 = metrics.policy_decisions_total.value("active")
        with features.gate("TPUPlacementSolver", True), \
                features.gate("TPULearnedPlacer", True), \
                features.gate("ColumnarCore", True):
            # Columnar core ON (docs/columnar.md graduation plan): the
            # sweep's per-rate determinism assertions gate the mirror.
            cluster = make_cluster(placement=placement)
            cluster.add_topology(
                topology_key, num_domains=domains,
                nodes_per_domain=nodes_per_domain, capacity=8,
            )
            from ..api import FailurePolicy

            for j in range(jobsets):
                cluster.create_jobset(
                    make_jobset(f"pol-{i}-{j}")
                    .exclusive_placement(topology_key)
                    .failure_policy(FailurePolicy(max_restarts=4))
                    .replicated_job(
                        make_replicated_job("w").replicas(replicas)
                        .parallelism(pods_per_job)
                        .completions(pods_per_job).obj()
                    )
                    .obj()
                )
            cluster.run_until_stable()
            crashed = pod_crash_burst(cluster, injector, rate=crash_rate)
            cluster.run_until_stable()

        expected_pods = jobsets * replicas * pods_per_job
        bound = [p for p in cluster.pods.values() if p.spec.node_name]
        # A gang is stranded when a LIVE pod never got a node; leftover
        # Failed pod objects from the crash burst are not placements.
        unplaced = set()
        for pod in cluster.pods.values():
            if pod.status.phase in _LIVE_PHASES and not pod.spec.node_name:
                unplaced.add(pod.metadata.name.rsplit("-w-", 1)[0])
        per_domain: dict[str, set] = {}
        from ..api import keys as api_keys

        for pod in bound:
            node = cluster.nodes[pod.spec.node_name]
            per_domain.setdefault(
                node.labels[topology_key], set()
            ).add(pod.labels[api_keys.JOB_KEY])
        results.append({
            "rate": rate,
            "kind": kind,
            "gangs": jobsets,
            "pods_bound": len(bound),
            "pods_expected": expected_pods,
            "crashed_pods": len(crashed),
            "faults_injected": injector.injected_total("policy.inference"),
            "fallbacks": metrics.policy_fallbacks_total.total() - fallbacks0,
            "decisions_active": metrics.policy_decisions_total.value("active")
            - decisions0,
            "unplaced_gangs": len(unplaced),
            "double_booked_domains": sum(
                1 for ks in per_domain.values() if len(ks) > 1
            ),
        })
    return results


# ---------------------------------------------------------------------------
# Replicated-control-plane scenarios (jobset_tpu/ha, docs/ha.md)
# ---------------------------------------------------------------------------


def _suspended_gang_yaml(name: str, labels=None) -> bytes:
    """The canonical suspended-JobSet write body shared by every HA and
    partition write path (kill soaks, `bench.py --ha/--partition`, the
    partition harness) so the planes' write contracts cannot drift."""
    from ..api import serialization
    from ..testing import make_jobset, make_replicated_job

    js = (
        make_jobset(name)
        .replicated_job(
            make_replicated_job("w").replicas(1)
            .parallelism(1).completions(1).obj()
        )
        .suspend(True)
        .obj()
    )
    if labels:
        js.metadata.labels = dict(labels)
    return serialization.to_yaml(js).encode()


def ha_write_attempt(address: str, name: str, timeout: float = 5.0):
    """One suspended-JobSet create against a replicated control plane's
    serving address. Returns (status, warning): a 201 with warning=None
    is a MAJORITY-acknowledged write (the contract the HA soaks and
    `bench.py --ha` both assert on — shared here so they cannot drift);
    (None, None) means no listener / connection died mid-flight."""
    status, _, headers = _http_call(
        address, "POST", _API_JOBSETS, _suspended_gang_yaml(name),
        timeout=timeout,
    )
    return status, _header(headers, "Warning")


def _ha_write_storm(replica_set, writes: int, kill_after: Optional[int],
                    kill, clock=None, start: int = 0,
                    on_ack=None) -> dict:
    """Sequential suspended-JobSet creates against the replica set's
    serving address, retrying through failovers. `kill(replica_set)` fires
    after the `kill_after`-th CLEAN acknowledgement (a 2xx without a
    Warning header — the majority-acknowledged contract). Sequential,
    ack-gated writes keep every uid/resourceVersion assignment — and
    every per-point chaos arrival — a pure function of the write index,
    which is what makes two seeded runs byte-identical.

    ``on_ack(name, latency_s, write_retries)`` fires after every clean
    acknowledgement with the client-observed wall ack latency (first
    attempt -> clean 201) and the number of failed attempts this write
    rode through — the telemetry teeth's SLO observation point
    (``write_retries`` is the deterministic signal: wall latency across
    a failover depends on the lease's renewal phase)."""
    import time as _t

    def attempt(name: str):
        return ha_write_attempt(replica_set.address, name)

    acked: list[str] = []
    killed = None
    unavailable_s = 0.0
    retries = 0
    for i in range(start, start + writes):
        name = f"ha-{i:03d}"
        outage_started = None
        write_started = _t.monotonic()
        write_retries = 0
        acked_clean = False
        while True:
            status, warning = attempt(name)
            if status == 201 and warning is None:
                acked.append(name)
                acked_clean = True
                break
            if status == 409:
                # A retried create that actually landed before the ack was
                # lost: it exists on the serving leader; the NEXT write's
                # clean ack (same commit stream) covers its durability.
                break
            retries += 1
            write_retries += 1
            if outage_started is None:
                outage_started = _t.monotonic()
            replica_set.step()
            if clock is not None:
                clock.advance(replica_set.replicas[0].elector.retry_period)
            _t.sleep(0.02)
        if outage_started is not None:
            unavailable_s += _t.monotonic() - outage_started
        if acked_clean and on_ack is not None:
            on_ack(name, _t.monotonic() - write_started, write_retries)
        if (
            kill_after is not None
            and (i - start) + 1 == kill_after
            and killed is None
        ):
            killed = kill(replica_set)
    return {
        "acked": acked,
        "killed": killed,
        "retries": retries,
        "unavailable_s": round(unavailable_s, 3),
    }


def leader_kill(
    base_dir: str,
    writes: int = 18,
    kill_after: int = 8,
    replicas: int = 3,
    seed: int = 7,
    stream_latency_rate: float = 0.25,
    stream_latency_ms: float = 1.0,
    kill: bool = True,
) -> dict:
    """Seeded leader-kill storm (the HA acceptance scenario): 3 in-process
    replicas, sequential write storm, the leader hard-killed mid-storm
    after `kill_after` majority-acknowledged writes; a follower waits out
    the lease, catches up, replays the committed log, and takes over the
    serving port. `replication.stream` latency faults ride along at
    `stream_latency_rate` so the ship path is exercised under jitter
    without perturbing quorum arithmetic.

    Returns the acked-write list, the final serialized store state of the
    surviving leader, and the injector's log — a run with `kill=False` is
    the no-kill baseline the caller asserts byte-identity against (zero
    majority-acknowledged JobSets lost).

    The telemetry plane rides along as teeth (docs/observability.md): a
    ``Telemetry`` on its OWN FakeClock ticks once per acknowledged write,
    each ack observed into ``jobset_slo_time_to_admission_seconds`` as
    its MODELED client latency — 0 for a write acked on the first
    attempt (in-process acks are instant at storm timescale), the lease
    duration for a write that rode the failover (the client's exposure
    window; wall retry timing depends on the lease's renewal phase, and
    seeded teeth must classify good/bad identically on every run). A
    kill run therefore fires ``JobSetControlPlaneFailover`` plus the SLO
    fast-burn alert while the ``kill=False`` baseline fires NOTHING, and
    the ``alerts`` transition log in the result is byte-identical across
    two seeded runs (transition timestamps are FakeClock tick
    indices)."""
    from ..core import metrics
    from ..ha import ReplicaSet
    from ..obs.tsdb import Telemetry
    from ..utils.clock import FakeClock

    injector = FaultInjector(seed=seed)
    if stream_latency_rate > 0:
        from .injector import KIND_LATENCY

        injector.add_rule(
            "replication.stream", KIND_LATENCY,
            rate=stream_latency_rate, delay_s=stream_latency_ms / 1000.0,
        )
    replica_set = ReplicaSet(
        base_dir, n=replicas,
        lease_duration=0.5, retry_period=0.1, tick_interval=0.05,
        injector=injector,
        # Columnar core ON for promoted leaders' clusters
        # (docs/columnar.md graduation plan): the soak's byte-identity
        # gate (kill vs no-kill final state) runs on the mirror.
        cluster_factory=_columnar_cluster,
    ).start()
    tel_clock = FakeClock(0.0)
    telemetry = Telemetry(clock=tel_clock, interval=1.0)
    try:
        # Baseline sample at t=0, then one tick per acked write at t=1,
        # 2, ... — tick times are write indices, not wall time, so the
        # alert transition log is a pure function of the seed.
        telemetry.tick()

        lease_duration = replica_set.replicas[0].elector.lease_duration

        def on_ack(name: str, latency_s: float, write_retries: int) -> None:
            metrics.slo_time_to_admission_seconds.observe(
                0.0 if write_retries == 0 else lease_duration
            )
            tel_clock.advance(1.0)
            telemetry.tick()

        result = _ha_write_storm(
            replica_set, writes,
            kill_after if kill else None,
            lambda rs: rs.kill_leader(),
            on_ack=on_ack,
        )
        leader = replica_set.leader()
        result.update({
            "scenario": "leader_kill",
            "writes": writes,
            "replicas": replicas,
            "seed": seed,
            "leader": leader.replica_id,
            "final_state": leader.store.serialized_state(),
            "final_seq": leader.store.seq,
            "commit_seq": leader.store.commit_seq,
            "resource_version": leader.store.resource_version,
            "injection_log": injector.log_snapshot(),
            "alerts": telemetry.alerts.transition_log(),
            "alerts_firing": telemetry.alerts.firing(),
        })
        return result
    finally:
        replica_set.stop()


# ---------------------------------------------------------------------------
# Flow-control scenarios (jobset_tpu/flow, docs/flow.md)
# ---------------------------------------------------------------------------

# Storm-sized priority levels for `thundering_herd`: tiny seat pools so a
# sequential driver saturates them with `FlowController.hold`, and ZERO
# queue-wait budgets so a parked arrival sheds instantly instead of
# sleeping — the whole storm runs in virtual time. workload-low carries
# no queues at all (saturation sheds), workload-high keeps small sharded
# queues (its sheds are wait-budget timeouts), and the single watch seat
# forces the thread-free partial-batch path.
def _herd_levels():
    from ..flow import PriorityLevel

    return (
        PriorityLevel("exempt", seats=0),
        PriorityLevel("system", seats=4, queues=2, queue_length=8,
                      queue_wait_s=0.0),
        PriorityLevel("workload-high", seats=2, queues=2, queue_length=2,
                      queue_wait_s=0.0),
        PriorityLevel("workload-low", seats=2, queues=0),
        PriorityLevel("watch", seats=1),
    )


def thundering_herd(
    arrivals: int = 240,
    tenants: int = 6,
    seed: int = 23,
    latency_fault_rate: float = 0.1,
    profiled: bool = False,
) -> dict:
    """Seeded overload storm against a flow-controlled controller server
    (the flow plane's acceptance scenario, driven by ``bench.py
    --overload``'s deterministic sibling and the flow tests).

    A sequential driver — every arrival completes before the next, so
    the run is a pure function of the seed — fires a mixed multi-tenant
    request storm through ``ControllerServer._route`` while
    ``FlowController.hold`` keeps the workload/watch seat pools
    saturated (the stand-in for a real concurrent herd):

    * phase ``storm``: low-priority creates shed 429 (no queues:
      ``saturated``), high-priority creates shed 429 at the zero wait
      budget (``timeout``) until one held seat is released mid-storm —
      after which high traffic lands while low traffic keeps shedding
      (the fairness split); watches answer immediate partial batches
      with retry hints; ``/debug/health`` (exempt) always executes.
    * phase ``recover``: every hold is released and the tail of the
      storm lands clean.

    ``apiserver.request`` latency faults (zero-delay, so the log records
    arrivals without costing wall time) ride along at
    ``latency_fault_rate`` — they only see requests that SURVIVED
    admission, pinning the shed-before-everything contract into the
    injection log.

    Returns the flow decision log, the injector's injection log, and the
    final cluster state — all deterministic: two runs with the same seed
    are byte-identical (``tests/test_flow.py`` asserts it), and no
    429'd create may leave an object behind (``leaked_shed_objects``
    must come back empty).

    ``profiled=True`` runs the storm with the whole continuous-profiling
    plane attached — live stack sampler, contention-instrumented
    server/cluster locks, ``/debug/profile`` read at the end — and
    returns the (wall-clock-dependent) liveness evidence under a
    ``profile`` key. Everything OUTSIDE that key stays byte-identical
    to an unprofiled run: the profiler only reads frames and times lock
    waits, it never touches decision state.
    """
    import random

    from ..api import serialization
    from ..core import make_cluster
    from ..flow import FlowController
    from ..obs.tsdb import Telemetry
    from ..server import ControllerServer
    from ..testing import make_jobset, make_replicated_job
    from ..utils.clock import FakeClock
    from .injector import KIND_LATENCY

    injector = FaultInjector(seed=seed)
    if latency_fault_rate > 0:
        injector.add_rule(
            "apiserver.request", KIND_LATENCY,
            rate=latency_fault_rate, delay_s=0.0,
        )
    flow = FlowController(levels=_herd_levels(), seed=seed)
    # Columnar core ON (docs/columnar.md graduation plan): the storm's
    # seeded byte-identity gate (tests/test_flow.py) runs on the mirror.
    cluster = _columnar_cluster(clock=FakeClock())
    # Never started: requests are driven straight through _route (no
    # handler threads, no pump — the arrival order IS the program order).
    server = ControllerServer(
        cluster=cluster, tick_interval=3600.0,
        injector=injector, flow=flow,
    )
    profiler = contention_prof = None
    locks_instrumented: list[str] = []
    if profiled:
        from ..obs.contention import ContentionProfiler
        from ..obs.profile import StackProfiler

        # Install BEFORE any driving: the server is never start()ed, so
        # no thread has touched its locks yet (the race harness's swap
        # rule), and the lock-wait histograms cover the whole storm.
        contention_prof = ContentionProfiler()
        locks_instrumented = sorted(
            contention_prof.instrument(cluster, "cluster")
            + contention_prof.instrument(server, "server")
        )
        server.profiler = profiler = StackProfiler(hz=200.0)
        profiler.start()
    api = f"{server.API_PREFIX}/namespaces/default/jobsets"
    rng = random.Random(seed)
    # Telemetry teeth on the SAME virtual clock: one tick per arrival at
    # 0.25 s spacing (4 arrivals/s — herd pacing, so the storm's shed
    # rate clears the default alert's 1/s threshold while the recover
    # tail keeps it firing inside the 60 s rate window). Every tick time,
    # sample, and alert transition is a pure function of the seed.
    telemetry = Telemetry(clock=cluster.clock, interval=0.25)

    def jobset_body(name: str, priority) -> bytes:
        js = (
            make_jobset(name)
            .replicated_job(
                make_replicated_job("w").replicas(1)
                .parallelism(1).completions(1).obj()
            )
            .suspend(True)
            .obj()
        )
        if priority is not None:
            js.spec.priority = priority
        return serialization.to_yaml(js).encode()

    statuses: dict[str, dict[int, int]] = {}
    shed_creates: list[str] = []
    acked_creates: list[str] = []
    n = 0

    def drive(phase: str) -> None:
        nonlocal n
        n += 1
        tenant = rng.randrange(tenants)
        op = rng.choices(
            ("create-low", "create-high", "list", "watch", "health"),
            weights=(5, 2, 2, 1, 1),
        )[0]
        headers = {"user-agent": f"herd-tenant-{tenant}"}
        if op == "create-low":
            name = f"herd-{n:04d}"
            result = server._route(
                "POST", api, jobset_body(name, None), headers=headers
            )
        elif op == "create-high":
            name = f"herd-{n:04d}"
            result = server._route(
                "POST", api, jobset_body(name, 120), headers=headers
            )
        elif op == "list":
            result = server._route("GET", api, b"", headers=headers)
        elif op == "watch":
            result = server._route(
                "GET", f"{api}?watch=1&resourceVersion=0&timeoutSeconds=0",
                b"", headers=headers,
            )
        else:
            result = server._route(
                "GET", "/debug/health", b"", headers=headers
            )
        status = result[0]
        per = statuses.setdefault(phase, {})
        per[status] = per.get(status, 0) + 1
        if op.startswith("create"):
            (acked_creates if status == 201 else shed_creates).append(name)
        cluster.clock.advance(0.25)
        telemetry.tick()

    try:
        telemetry.tick()  # t=0 baseline sample before the storm
        held_low = flow.hold("workload-low", 2)
        held_high = flow.hold("workload-high", 2)
        held_watch = flow.hold("watch", 1)
        for i in range(arrivals):
            if i == arrivals // 2:
                # Mid-storm partial recovery: ONE high seat frees, so
                # high-priority writes start landing while low-priority
                # traffic keeps shedding — the fairness split the plane
                # exists for.
                flow.release(held_high.pop())
            drive("storm")
        for ticket in held_low + held_high + held_watch:
            flow.release(ticket)
        for _ in range(max(1, arrivals // 3)):
            drive("recover")
    finally:
        if profiler is not None:
            profiler.stop()
        server._stop.set()
        server._httpd.server_close()

    profile_block = None
    if profiled:
        # The liveness evidence the profiling soak gates on: the debug
        # surface answered, stacks were sampled, the lock and JIT
        # telemetry rode along. Wall-clock-dependent by nature — callers
        # comparing byte-identity must pop this key first.
        resp = server._route("GET", "/debug/profile", b"")
        payload = resp[1] if resp[0] == 200 else {}
        profile_block = {
            "status": resp[0],
            "samples": payload.get("samples", 0),
            "roles": sorted(payload.get("roles", {})),
            "locks_instrumented": locks_instrumented,
            "lock_waits": sorted(payload.get("locks", {})),
            "jit_kernels": sorted(payload.get("jit", {})),
        }
        contention_prof.uninstall()

    with server.lock:
        leaked = [
            name for name in shed_creates
            if cluster.get_jobset("default", name) is not None
        ]
        final_state = {
            "resourceVersion": server._watch_rv,
            "jobsets": [
                {
                    "namespace": ns,
                    "name": name,
                    "uid": js.metadata.uid,
                    "priority": js.spec.priority,
                }
                for (ns, name), js in sorted(cluster.jobsets.items())
            ],
        }
    # Stringified statuses so the dict survives a JSON round trip
    # unchanged (byte-identity is asserted over json.dumps).
    return {
        "scenario": "thundering_herd",
        "seed": seed,
        "tenants": tenants,
        "arrivals": n,
        "statuses": {
            phase: {str(code): count for code, count in sorted(per.items())}
            for phase, per in sorted(statuses.items())
        },
        "acked_creates": len(acked_creates),
        "shed_creates": len(shed_creates),
        "leaked_shed_objects": leaked,
        "rejected_total": flow.rejected_total(),
        "flow": flow.snapshot(),
        "decision_log": flow.log_snapshot(),
        "injection_log": injector.log_snapshot(),
        "final_state": final_state,
        "alerts": telemetry.alerts.transition_log(),
        "alerts_firing": telemetry.alerts.firing(),
        **({"profile": profile_block} if profile_block is not None else {}),
    }


# ---------------------------------------------------------------------------
# Partition-tolerance scenarios (chaos/net.py + jobset_tpu/verify, docs/ha.md
# "Consistency guarantees"). Each drives a replica set through a seeded
# network-fault schedule while recording every client-visible operation
# into a verify.HistoryRecorder, and gates acceptance on the consistency
# checker: zero majority-acked loss, one unfenced leader per term,
# session-monotonic reads, and a linearizable register. A run with
# read_fence=False re-opens the minority-stale-read hole, and the checker
# FAILS it — the proof the checker has teeth.
# ---------------------------------------------------------------------------

_API_JOBSETS = "/apis/jobset.x-k8s.io/v1alpha2/namespaces/default/jobsets"

# The single-object register the linearizability invariant covers: one
# JobSet whose labels["v"] is the register value (labels are the only
# freely-mutable field, so updates replay through the full PUT path).
REGISTER_NAME = "reg"
REGISTER_KEY = f"default/{REGISTER_NAME}"


def _http_call(address: str, method: str, path: str, body=None,
               timeout: float = 5.0):
    """One raw HTTP round trip; returns (status, parsed-json-or-None,
    headers dict). status None = no listener / connection died."""
    import json as _json
    import urllib.error
    import urllib.request

    req = urllib.request.Request(
        f"http://{address}{path}", data=body, method=method,
        headers={"Content-Type": "application/yaml"} if body else {},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            data = resp.read()
            headers = dict(resp.headers)
            status = resp.status
    except urllib.error.HTTPError as exc:
        data = exc.read()
        headers = dict(exc.headers)
        status = exc.code
    except (urllib.error.URLError, OSError):
        return None, None, {}
    try:
        parsed = _json.loads(data)
    except ValueError:
        parsed = None
    return status, parsed, headers


def _header(headers: dict, name: str):
    for key, value in headers.items():
        if key.lower() == name.lower():
            return value
    return None


def _replication_identity(headers: dict):
    """(term, replica) from the response's replication identity headers
    (server.py stamps X-Jobset-Term / X-Jobset-Replica on every API
    response of a replicated server)."""
    term = _header(headers, "X-Jobset-Term")
    return (
        int(term) if term is not None else None,
        _header(headers, "X-Jobset-Replica"),
    )


class PartitionHarness:
    """Shared driver for the partition scenarios: a `ha.ReplicaSet` whose
    injector carries a seeded `PartitionPlan`, plus history-recorded
    read/write primitives. Writes are ack-gated by default (retried
    through failovers until a CLEAN majority acknowledgement, recorded as
    ONE operation) so the committed history — and with it every recorded
    status, value, and resourceVersion — is a pure function of the
    operation sequence, never of failover timing; raw fencing terms are
    the one timing-dependent field, which `HistoryRecorder.normalized()`
    maps to dense indices for the byte-identity gate."""

    def __init__(self, base_dir: str, seed: int = 13, replicas: int = 3,
                 read_fence: bool = True):
        from ..ha import ReplicaSet
        from ..verify import HistoryRecorder
        from .net import PartitionPlan

        self.seed = seed
        self.injector = FaultInjector(seed=seed)
        self.plan = PartitionPlan(seed=seed, injector=self.injector)
        self.recorder = HistoryRecorder()
        # HTTP attempts the most recent write() needed to reach its
        # terminal status (partition_flap's first-attempt-clean stat).
        self.last_write_attempts = 0
        self.replica_set = ReplicaSet(
            base_dir, n=replicas,
            lease_duration=0.4, retry_period=0.1, tick_interval=0.05,
            injector=self.injector, read_fence=read_fence,
        ).start()

    def stop(self) -> None:
        self.replica_set.stop()

    # -- primitives ---------------------------------------------------------

    @staticmethod
    def _gang_body(name: str, labels=None) -> bytes:
        return _suspended_gang_yaml(name, labels)

    def write(self, session: str, name: str, labels=None,
              update: bool = False, retry: bool = True,
              deadline_s: float = 30.0):
        """One recorded write: POST (create) or PUT (update) of `name`.
        retry=True keeps attempting — stepping the replica set between
        tries — until a clean majority ack (or a 409: the write landed
        under a lost ack; the next clean ack covers it); retry=False
        records whatever the single attempt answered (Warning probes,
        no-listener outages)."""
        import time as _t

        path = _API_JOBSETS + (f"/{name}" if update else "")
        body = self._gang_body(name, labels)
        op = self.recorder.invoke(
            session, "write", f"default/{name}",
            value=(labels or {}).get("v"),
        )
        deadline = _t.monotonic() + deadline_s
        self.last_write_attempts = 0
        while True:
            self.last_write_attempts += 1
            status, _payload, headers = _http_call(
                self.replica_set.address,
                "PUT" if update else "POST", path, body,
            )
            ok = status is not None and 200 <= status < 300
            clean = ok and not _header(headers, "Warning")
            term, replica = _replication_identity(headers)
            # Terminal outcomes: a clean majority ack, a 409 (the write
            # landed under a lost ack; the next clean ack covers its
            # durability), any client error, or single-shot mode. A
            # Warning 2xx under retry is NOT terminal — the retry's 409
            # closes the op, still unacked.
            if clean or not retry or status == 409 or (
                status is not None and 400 <= status < 500
                and status != 409
            ):
                self.recorder.complete(
                    op, ok or status == 409, status=status,
                    term=term, replica=replica, acked=clean,
                )
                return status
            if _t.monotonic() > deadline:
                raise RuntimeError(
                    f"write {name} never acknowledged within {deadline_s}s"
                )
            self.replica_set.step()
            _t.sleep(0.02)

    def read(self, session: str, server=None):
        """One single-shot recorded read of the jobset collection (items
        + the journal resourceVersion — the list half of list-then-watch,
        so the rv is client-visible state). `server` targets a specific
        replica's in-process surface — the zombie-leader read the fence
        exists for; default goes over HTTP to the serving address.
        Returns (status, rv, register value)."""
        op = self.recorder.invoke(session, "read", REGISTER_KEY)
        if server is not None:
            result = server._route("GET", _API_JOBSETS, b"")
            status, payload = result[0], result[1]
            headers = dict(result[3]) if len(result) > 3 else {}
        else:
            status, payload, headers = _http_call(
                self.replica_set.address, "GET", _API_JOBSETS
            )
        ok = status is not None and 200 <= status < 300
        rv = value = None
        if ok and isinstance(payload, dict):
            rv = payload.get("resourceVersion")
            for item in payload.get("items", ()):
                meta = item.get("metadata") or {}
                if meta.get("name") == REGISTER_NAME:
                    value = (meta.get("labels") or {}).get("v")
        term, replica = _replication_identity(headers)
        self.recorder.complete(
            op, ok, status=status, value=value, rv=rv,
            term=term, replica=replica,
        )
        return status, rv, value

    # -- topology control ---------------------------------------------------

    def isolate(self, replica_id: str, step: int) -> None:
        """Cut every link between `replica_id` and the rest, both
        directions, at plan step `step` (logged cut transitions)."""
        self.plan.isolate(
            replica_id,
            [r.replica_id for r in self.replica_set.replicas],
            at=step,
        )

    def split_all(self, step: int) -> None:
        """Full N-way split: every directed link cut."""
        ids = [r.replica_id for r in self.replica_set.replicas]
        for src in ids:
            for dst in ids:
                if src != dst:
                    self.plan.cut(src, dst, at=step)
        self.plan.advance(step)

    def await_leader(self, other_than=None, deadline_s: float = 30.0):
        """Step the supervisor until a leader exists (and differs from
        `other_than`, when given)."""
        import time as _t

        deadline = _t.monotonic() + deadline_s
        while _t.monotonic() < deadline:
            self.replica_set.step()
            leader = self.replica_set.leader()
            if leader is not None and leader is not other_than:
                return leader
            _t.sleep(0.03)
        raise RuntimeError("no leader elected within the deadline")

    def await_lost_quorum(self, replica, deadline_s: float = 30.0) -> None:
        """Wait until `replica`'s coordinator has OBSERVED quorum loss
        (the pump's idle re-ships accrue the failures within a few
        ticks). Scenario reads against a minority leader come after
        this, so their outcome is the fence's deterministic
        fenced/lost_quorum short-circuit — not a race against the
        read_fence_age_s freshness window."""
        import time as _t

        deadline = _t.monotonic() + deadline_s
        while _t.monotonic() < deadline:
            coordinator = replica.coordinator
            if coordinator is None or any(coordinator.health_flags()):
                return
            _t.sleep(0.02)
        raise RuntimeError("quorum loss never observed")

    def await_no_leader(self, deadline_s: float = 30.0) -> None:
        """Step until no replica serves (the quorumless split state)."""
        import time as _t

        deadline = _t.monotonic() + deadline_s
        while _t.monotonic() < deadline:
            self.replica_set.step()
            if self.replica_set.leader() is None:
                return
            _t.sleep(0.03)
        raise RuntimeError("a leader kept serving past the deadline")

    def reconcile_replica(self, replica) -> dict:
        """Post-heal log reconciliation of a (demoted or lagging)
        follower against the quorum — the rejoin path: divergent tails
        from its deposed epoch are truncated, the quorum's tail copied."""
        from ..ha.replication import catch_up

        return catch_up(
            replica.log,
            self.replica_set.peers_for(replica),
            cluster_size=len(self.replica_set.replicas),
        )

    # -- verdict ------------------------------------------------------------

    def result(self, scenario: str, extra=None) -> dict:
        """Final-state capture + the consistency checker verdict. The
        byte-identity artifact is (injection_log, history, checker,
        final_keys, final_seq, commit_seq) — deliberately NOT the
        blocked-delivery counters, which depend on how many read-fence
        probes and retries wall-clock timing produced."""
        import json as _json

        from ..verify import check_history

        leader = self.replica_set.leader()
        serialized = leader.store.serialized_state()["jobsets"]
        final_state = {}
        for key, payload in serialized.items():
            value = None
            if key == REGISTER_KEY:
                manifest = _json.loads(payload).get("manifest") or {}
                meta = manifest.get("metadata") or {}
                value = (meta.get("labels") or {}).get("v")
            final_state[key] = value
        report = check_history(
            self.recorder.snapshot(),
            final_state=final_state,
            register_key=REGISTER_KEY,
        )
        return {
            "scenario": scenario,
            "seed": self.seed,
            "leader": leader.replica_id,
            "history": self.recorder.normalized(),
            "checker": report.to_dict(),
            "injection_log": self.injector.log_snapshot(),
            "final_keys": sorted(final_state),
            "final_seq": leader.store.seq,
            "commit_seq": leader.store.commit_seq,
            "blocked_links": sorted(
                f"{src}->{dst}" for src, dst in self.plan.blocked
            ),
            **(extra or {}),
        }


def leader_isolated(base_dir: str, seed: int = 13,
                    read_fence: bool = True) -> dict:
    """The canonical partition scenario: the leader is cut from both
    followers (symmetric), keeps acking only with quorum Warnings, the
    majority side elects a successor, and the deposed leader's surface —
    still holding a connected client — is asked for a read AFTER the
    session has seen the new epoch. With the read fence on, that zombie
    read answers 503 + leader hint and the checker passes; with
    read_fence=False the stale cluster answers, and the checker fails on
    session monotonicity AND register linearizability — the teeth test.
    Heal + reconciliation then brings the deposed leader's log to the
    exact quorum position, ghost tail truncated."""
    harness = PartitionHarness(base_dir, seed=seed, read_fence=read_fence)
    try:
        replica_set = harness.replica_set
        # Healthy baseline: ledger writes + the register at v=1, v=2.
        for i in range(4):
            harness.write("writer", f"iso-{i:03d}")
        harness.write("writer", REGISTER_NAME, labels={"v": "1"})
        harness.write("writer", REGISTER_NAME, labels={"v": "2"},
                      update=True)
        harness.read("reader")
        old = replica_set.leader()
        old_server = old.server
        # Isolate the leader. Its next write applies locally but cannot
        # reach a quorum: 2xx + Warning, recorded as indeterminate.
        harness.isolate(old.replica_id, step=1)
        harness.write("writer", "iso-warn", retry=False)
        # A read against the isolated leader once it has OBSERVED quorum
        # loss: the fence answers 503 (it cannot prove quorum-fresh
        # state); unfenced it serves — still legal here, nothing newer
        # exists yet.
        harness.await_lost_quorum(old)
        harness.read("reader", server=old_server)
        # Majority side elects a successor and makes progress.
        new = harness.await_leader(other_than=old)
        harness.write("writer", "iso-after")
        harness.write("writer", REGISTER_NAME, labels={"v": "3"},
                      update=True)
        harness.read("reader")
        # THE zombie read: same session, after observing the new epoch,
        # against the deposed leader's still-reachable surface.
        harness.read("reader", server=old_server)
        # Heal and reconcile the deposed leader to the exact quorum log.
        harness.plan.heal_all(step=2)
        rejoin = harness.reconcile_replica(old)
        position = old.log.position()
        return harness.result("leader_isolated", extra={
            "read_fence": read_fence,
            "isolated": old.replica_id,
            "rejoin": rejoin,
            "follower_position": position,
            "converged": (
                position["lastSeq"] == new.store.seq
                and position["commitSeq"] == new.store.commit_seq
            ),
        })
    finally:
        harness.stop()


def split_3way(base_dir: str, seed: int = 17) -> dict:
    """Full 3-way split: every directed link cut. Nobody can prove a
    quorum, so after the deposed leader steps down NO replica serves
    (writes answer nothing at all — unavailability is the correct
    partition-tolerant behavior, not split-brain). On heal the original
    leader re-promotes — its own log ranks most up-to-date — and its
    Warning-acked write from the split is committed by the first
    post-promotion replicate (Raft's prior-term entry adoption)."""
    harness = PartitionHarness(base_dir, seed=seed)
    try:
        replica_set = harness.replica_set
        for i in range(3):
            harness.write("writer", f"split-{i:03d}")
        harness.write("writer", REGISTER_NAME, labels={"v": "1"})
        harness.read("reader")
        harness.split_all(step=1)
        # One write against the still-serving leader: quorum Warning.
        harness.write("writer", "split-warn", retry=False)
        # The leader loses quorum and steps down; elections fail
        # (establish_term cannot reach a majority) until the heal.
        harness.await_no_leader()
        for i in range(3):
            harness.write("writer", f"split-dark-{i}", retry=False)
        harness.read("reader")
        harness.plan.heal_all(step=2)
        leader = harness.await_leader()
        harness.write("writer", "split-after")
        harness.read("reader")
        serialized = leader.store.serialized_state()["jobsets"]
        return harness.result("split_3way", extra={
            "warn_write_committed": "default/split-warn" in serialized,
        })
    finally:
        harness.stop()


def partition_flap(base_dir: str, seed: int = 19, writes: int = 10,
                   period: int = 2) -> dict:
    """Flapping link between the leader and one follower while a write
    storm runs: the quorum holds through every flap (leader + the other
    follower), so availability stays 100% and every write acks clean on
    the first attempt; the flapped follower lags during cut intervals
    and is caught up from the resend buffer on each heal. Cut AND heal
    transitions land in the injection log at their scheduled steps (the
    per-link seeded jitter included), so two seeded runs log identical
    flap schedules."""
    harness = PartitionHarness(base_dir, seed=seed)
    try:
        replica_set = harness.replica_set
        leader = replica_set.leader()
        victim = next(
            r for r in replica_set.replicas if r is not leader
        )
        transitions = harness.plan.flap(
            leader.replica_id, victim.replica_id,
            at=1, until=writes + 1, period=period, symmetric=True,
        )
        harness.write("writer", REGISTER_NAME, labels={"v": "1"})
        clean_first_attempt = 0
        for i in range(writes):
            harness.plan.advance(i + 1)
            status = harness.write("writer", f"flap-{i:03d}")
            # Honest stat: a clean ack on the FIRST HTTP attempt — not
            # merely "the internal retry loop eventually got there".
            if status == 201 and harness.last_write_attempts == 1:
                clean_first_attempt += 1
        harness.plan.advance(writes + 1)  # terminal heal
        harness.write("writer", REGISTER_NAME, labels={"v": "2"},
                      update=True)
        harness.read("reader")
        # One post-heal write re-probes the flapped follower and ships
        # the whole gap from the resend buffer: exact convergence.
        harness.write("writer", "flap-final")
        position = victim.log.position()
        return harness.result("partition_flap", extra={
            "flap_transitions": transitions,
            "clean_first_attempt": clean_first_attempt,
            "victim": victim.replica_id,
            "follower_position": position,
            "converged": position["lastSeq"] == leader.store.seq,
        })
    finally:
        harness.stop()


def asymmetric_link(base_dir: str, seed: int = 23,
                    writes: int = 6) -> dict:
    """One-directional cut (leader -> follower only): the leader cannot
    ship frames to the victim — its lag grows, the contact report flags
    the link — but the REVERSE direction still works, so the victim can
    pull the tail itself via catch-up (reconciliation over the healthy
    direction). Quorum holds via the other follower throughout; after
    the heal one ship converges the victim exactly."""
    harness = PartitionHarness(base_dir, seed=seed)
    try:
        replica_set = harness.replica_set
        leader = replica_set.leader()
        victim = next(
            r for r in replica_set.replicas if r is not leader
        )
        harness.write("writer", REGISTER_NAME, labels={"v": "1"})
        harness.plan.cut(leader.replica_id, victim.replica_id, at=1)
        harness.plan.advance(1)
        for i in range(writes):
            harness.write("writer", f"asym-{i:03d}")
        harness.read("reader")
        lag_during_cut = leader.coordinator.follower_lag()[
            victim.replica_id
        ]
        # The healthy reverse direction: the victim pulls the missing
        # tail itself (catch-up probes leader + other follower — its own
        # outbound links are NOT cut).
        pull = harness.reconcile_replica(victim)
        pulled_position = victim.log.position()
        harness.plan.heal_all(step=2)
        harness.write("writer", "asym-final")
        harness.read("reader")
        position = victim.log.position()
        return harness.result("asymmetric_link", extra={
            "victim": victim.replica_id,
            "lag_during_cut": lag_during_cut,
            "reverse_pull": pull,
            "pulled_to": pulled_position["lastSeq"],
            "follower_position": position,
            "converged": position["lastSeq"] == leader.store.seq,
        })
    finally:
        harness.stop()


def follower_kill(
    base_dir: str,
    writes: int = 12,
    kill_after: int = 4,
    rejoin_after: int = 8,
    replicas: int = 3,
    seed: int = 7,
) -> dict:
    """Follower-loss storm: a follower is hard-killed mid-storm — the
    leader keeps acknowledging (quorum is leader + the surviving
    follower) — then rejoins and must catch up to the exact log. Returns
    write availability plus the rejoined replica's reconciliation stats
    (the caller asserts position convergence and zero failed acks)."""
    from ..ha import ReplicaSet

    injector = FaultInjector(seed=seed)
    replica_set = ReplicaSet(
        base_dir, n=replicas,
        lease_duration=0.5, retry_period=0.1, tick_interval=0.05,
        injector=injector,
    ).start()
    try:
        killed: list[str] = []
        rejoin_stats: dict = {}

        acked: list[str] = []
        for i in range(writes):
            result = _ha_write_storm(
                replica_set, 1, None, lambda rs: None, start=i,
            )
            acked.extend(result["acked"])
            if i + 1 == kill_after:
                killed.append(replica_set.kill_follower())
            if i + 1 == rejoin_after and killed:
                rejoin_stats = replica_set.rejoin(killed[0])
        leader = replica_set.leader()
        victim = next(
            r for r in replica_set.replicas
            if r.replica_id == killed[0]
        )
        return {
            "scenario": "follower_kill",
            "writes": writes,
            "killed": killed[0] if killed else None,
            "acked": len(acked),
            "rejoin": rejoin_stats,
            "leader_seq": leader.store.seq,
            "follower_position": victim.log.position(),
            "injection_log": injector.log_snapshot(),
        }
    finally:
        replica_set.stop()

# ---------------------------------------------------------------------------
# Sharded control plane scenarios (jobset_tpu/shard, docs/sharding.md)
# ---------------------------------------------------------------------------


class ShardedHarness:
    """Driver for the sharded region-fault scenarios: a
    `shard.ShardedControlPlane` whose injector carries a seeded
    `PartitionPlan`, plus history-recorded primitives in the two rv
    scopes the cross-shard checker distinguishes — per-shard ops (keys
    hash to a shard; rvs are that shard's journal) and router ops
    (cross-shard merged LISTs; rvs are the front door's merged
    journal). Writes are ack-gated like the PartitionHarness's, so the
    committed history is a pure function of the operation sequence."""

    ROUTER_KEY = "__router__"

    def __init__(self, base_dir: str, seed: int = 31, shards: int = 2,
                 read_fence: bool = True, spread_shards=(),
                 auto_migrate: bool = False,
                 placement_stickiness_ms: float = 0.0,
                 migration_hysteresis_steps: int = 2):
        from ..shard import ShardedControlPlane
        from ..verify import HistoryRecorder
        from .net import PartitionPlan

        self.seed = seed
        self.injector = FaultInjector(seed=seed)
        self.plan = PartitionPlan(seed=seed, injector=self.injector)
        self.recorder = HistoryRecorder()
        self.plane = ShardedControlPlane(
            base_dir, shards=shards, replicas_per_shard=3, seed=seed,
            injector=self.injector, lease_duration=0.4, retry_period=0.1,
            tick_interval=0.05, read_fence=read_fence,
            spread_shards=spread_shards,
            # Columnar core ON (docs/columnar.md graduation plan): the
            # scenario's seeded byte-identity gate runs on the mirror.
            cluster_factory=_columnar_cluster,
            auto_migrate=auto_migrate,
            placement_stickiness_ms=placement_stickiness_ms,
            migration_hysteresis_steps=migration_hysteresis_steps,
        )
        # Per-shard register names: deterministic probes into each
        # shard's keyspace.
        self.registers = {
            s: self.plane.map.key_for_shard(s, 0, prefix="reg")
            for s in range(shards)
        }

    def stop(self) -> None:
        self.plane.stop()

    def scope_of(self, op: dict):
        """The checker's shard_of: router-scope sentinel key, else the
        owning shard of the op's `namespace/name` key."""
        if op["key"] == self.ROUTER_KEY:
            return "router"
        ns, _, name = op["key"].partition("/")
        return self.plane.map.shard_for(ns, name)

    # -- primitives ---------------------------------------------------------

    def write(self, session: str, name: str, labels=None,
              update: bool = False, retry: bool = True,
              deadline_s: float = 30.0):
        """One recorded write via the FRONT DOOR, ack-gated like
        PartitionHarness.write: retried (stepping the shard groups)
        until a clean majority ack on the owning shard, a 409, or a
        client error; retry=False records the single attempt. Returns
        (status, attempts)."""
        import time as _t

        path = _API_JOBSETS + (f"/{name}" if update else "")
        body = _suspended_gang_yaml(name, labels)
        op = self.recorder.invoke(
            session, "write", f"default/{name}",
            value=(labels or {}).get("v"),
        )
        deadline = _t.monotonic() + deadline_s
        attempts = 0
        while True:
            attempts += 1
            status, _payload, headers = _http_call(
                self.plane.address,
                "PUT" if update else "POST", path, body,
            )
            ok = status is not None and 200 <= status < 300
            clean = ok and not _header(headers, "Warning")
            term, replica = _replication_identity(headers)
            if clean or not retry or status == 409 or (
                status is not None and 400 <= status < 500
                and status != 409
            ):
                self.recorder.complete(
                    op, ok or status == 409, status=status,
                    term=term, replica=replica, acked=clean,
                )
                return status, attempts
            if _t.monotonic() > deadline:
                raise RuntimeError(
                    f"write {name} never acknowledged within {deadline_s}s"
                )
            self.plane.step()
            _t.sleep(0.02)

    def read_shard(self, session: str, shard: int, server=None):
        """One recorded SHARD-scope read: the shard's jobset collection
        (register value + that shard's journal rv). Default goes over
        HTTP to the shard group's stable serving address; `server`
        targets a specific replica's in-process surface — the
        zombie-deposed-leader read the fence exists for."""
        register = self.registers[shard]
        op = self.recorder.invoke(session, "read", f"default/{register}")
        if server is not None:
            result = server._route("GET", _API_JOBSETS, b"")
            status, payload = result[0], result[1]
            headers = dict(result[3]) if len(result) > 3 else {}
        else:
            status, payload, headers = _http_call(
                self.plane.shard_groups[shard].address, "GET",
                _API_JOBSETS,
            )
        ok = status is not None and 200 <= status < 300
        rv = value = None
        if ok and isinstance(payload, dict):
            rv = payload.get("resourceVersion")
            for item in payload.get("items", ()):
                meta = item.get("metadata") or {}
                if meta.get("name") == register:
                    value = (meta.get("labels") or {}).get("v")
        term, replica = _replication_identity(headers)
        self.recorder.complete(
            op, ok, status=status, value=value, rv=rv,
            term=term, replica=replica,
        )
        return status, rv, value

    def read_router(self, session: str):
        """One recorded ROUTER-scope read: the cross-shard merged LIST
        through the front door; the rv is the merged journal head — the
        counter cross-shard session monotonicity is proven over."""
        op = self.recorder.invoke(session, "read", self.ROUTER_KEY)
        status, payload, _headers = _http_call(
            self.plane.address, "GET", _API_JOBSETS
        )
        ok = status is not None and 200 <= status < 300
        rv = None
        if ok and isinstance(payload, dict):
            rv = payload.get("resourceVersion")
        self.recorder.complete(op, ok, status=status, rv=rv)
        return status, rv

    # -- topology / leadership control --------------------------------------

    def await_leader(self, shard: int, other_than=None,
                     deadline_s: float = 30.0):
        import time as _t

        group = self.plane.shard_groups[shard]
        deadline = _t.monotonic() + deadline_s
        while _t.monotonic() < deadline:
            group.step()
            leader = group.leader()
            if leader is not None and leader is not other_than:
                return leader
            _t.sleep(0.03)
        raise RuntimeError(f"shard {shard} never elected a leader")

    def await_lost_quorum(self, replica, deadline_s: float = 30.0) -> None:
        import time as _t

        deadline = _t.monotonic() + deadline_s
        while _t.monotonic() < deadline:
            coordinator = replica.coordinator
            if coordinator is None or any(coordinator.health_flags()):
                return
            _t.sleep(0.02)
        raise RuntimeError("quorum loss never observed")

    # -- verdict ------------------------------------------------------------

    def result(self, scenario: str, extra=None) -> dict:
        """Final per-shard state capture + the CROSS-SHARD checker
        verdict (verify.check_sharded_history). Same byte-identity
        artifact discipline as PartitionHarness.result."""
        import json as _json

        from ..verify import check_sharded_history

        final_states: dict = {}
        register_keys: dict = {}
        leaders: dict = {}
        for shard, group in enumerate(
            self.plane.shard_groups[: self.plane.map.shards]
        ):
            leader = group.leader()
            leaders[shard] = leader.replica_id
            serialized = leader.store.serialized_state()["jobsets"]
            register_key = f"default/{self.registers[shard]}"
            register_keys[shard] = register_key
            state = {}
            for key, payload in serialized.items():
                value = None
                if key == register_key:
                    manifest = _json.loads(payload).get("manifest") or {}
                    meta = manifest.get("metadata") or {}
                    value = (meta.get("labels") or {}).get("v")
                state[key] = value
            final_states[shard] = state
        memberships = {
            shard: [list(s) for s in group.membership_log]
            for shard, group in enumerate(
                self.plane.shard_groups[: self.plane.map.shards]
            )
        }
        report = check_sharded_history(
            self.recorder.snapshot(),
            self.scope_of,
            final_states=final_states,
            register_keys=register_keys,
            memberships=memberships,
        )
        return {
            "scenario": scenario,
            "seed": self.seed,
            "shards": self.plane.map.shards,
            "homes": dict(self.plane.map.homes),
            "leaders": {str(k): v for k, v in sorted(leaders.items())},
            "history": self.recorder.normalized(),
            "checker": report.to_dict(),
            "injection_log": self.injector.log_snapshot(),
            "final_keys": {
                str(s): sorted(state) for s, state in final_states.items()
            },
            "memberships": {
                str(k): v for k, v in sorted(memberships.items())
            },
            **(extra or {}),
        }


def region_shard_consistency(base_dir: str, seed: int = 31,
                             read_fence: bool = True) -> dict:
    """THE sharded region-fault scenario (docs/sharding.md): a 2-shard
    plane over three regions, driven through one region isolation while
    the cross-shard consistency checker records everything.

    Shard 0 keeps the default latency-first placement (quorum-homed:
    leader + majority co-located); shard 1 is placed durability-first
    (SPREAD: one replica per region) so isolating its leader's region
    severs the leader from an out-of-region majority — the minority-
    leader situation the read fence exists for.

    Phases:

    1. Baseline: ledger writes + a per-shard register (v=1, v=2) on both
       shards through the front door; cross-shard merged reads.
    2. Region isolation: shard 1's home region is cut (plan-scheduled,
       both directions, front door included) and shard placement
       re-solves with the region priced out. A direct single-shot write
       against the isolated leader answers 2xx + quorum Warning
       (recorded indeterminate) and arms its idle-pump stepdown.
    3. Failover + the teeth: shard 1's out-of-region majority elects a
       successor and takes new writes (register v=3); shard 0 — homed
       elsewhere — must ack its fault-window writes clean on the FIRST
       attempt. The deposed leader's still-connected surface is then
       asked for a read by a session that already saw v=3 — with the
       read fence on it answers 503 and the cross-shard checker stays
       green; with ``read_fence=False`` it serves the stale register
       and the checker FAILS shard 1's linearizability/session
       monotonicity — the teeth run.
    4. Heal + reconcile: the region heals, placement re-solves back,
       and the deposed replica's log converges to the new leader's
       exact position (ghost tail truncated).
    """
    harness = ShardedHarness(base_dir, seed=seed, read_fence=read_fence,
                             spread_shards=(1,))
    try:
        plane = harness.plane
        teeth_shard, steady_shard = 1, 0
        teeth_home = plane.map.homes[teeth_shard]
        if teeth_home == plane.topology.front_door_region:
            raise RuntimeError(
                "seed places the teeth shard in the front-door region; "
                "pick another seed"
            )
        # Phase 1: baseline on both shards + cross-shard reads.
        for shard in (steady_shard, teeth_shard):
            for i in range(2):
                harness.write(
                    "writer",
                    plane.map.key_for_shard(shard, i, prefix="led"),
                )
            harness.write("writer", harness.registers[shard],
                          labels={"v": "1"})
            harness.write("writer", harness.registers[shard],
                          labels={"v": "2"}, update=True)
        harness.read_router("router-reader")
        harness.read_shard("reader", teeth_shard)
        group = plane.shard_groups[teeth_shard]
        old = group.leader()
        old_server = old.server
        # Phase 2: isolate the teeth shard's home region (the leader is
        # its only replica there — spread placement) and re-solve.
        planned = plane.isolate_region(teeth_home, step=1)
        # Single-shot write against the isolated leader's own surface:
        # applies locally, cannot reach a quorum -> 2xx + Warning,
        # recorded indeterminate; the pending unacked record arms the
        # idle pump's quorum-failure stepdown.
        warn_op = harness.recorder.invoke(
            "writer", "write",
            f"default/{plane.map.key_for_shard(teeth_shard, 9, prefix='warn')}",
        )
        status, _payload, headers = _http_call(
            group.address, "POST", _API_JOBSETS,
            _suspended_gang_yaml(
                plane.map.key_for_shard(teeth_shard, 9, prefix="warn")
            ),
        )
        term, replica = _replication_identity(headers)
        harness.recorder.complete(
            warn_op, status is not None and 200 <= (status or 0) < 300,
            status=status, term=term, replica=replica,
            acked=bool(status and 200 <= status < 300
                       and not _header(headers, "Warning")),
        )
        harness.await_lost_quorum(old)
        # Phase 3: failover to the out-of-region majority + the teeth.
        new = harness.await_leader(teeth_shard, other_than=old)
        steady_attempts = []
        for i in range(2, 4):
            _status, attempts = harness.write(
                "writer",
                plane.map.key_for_shard(steady_shard, i, prefix="led"),
            )
            steady_attempts.append(attempts)
        harness.write("writer", harness.registers[teeth_shard],
                      labels={"v": "3"}, update=True)
        harness.read_router("router-reader")
        harness.read_shard("reader", teeth_shard)
        # THE zombie read: same session, after observing v=3, against
        # the deposed leader's still-reachable surface.
        harness.read_shard("reader", teeth_shard, server=old_server)
        # Phase 4: heal, re-solve back, reconcile the deposed replica.
        plane.heal_region(teeth_home, step=2)
        victim = next(
            r for r in group.replicas
            if r.replica_id == old.replica_id
        )
        import time as _t

        rejoin = None
        deadline = _t.monotonic() + 30.0
        while rejoin is None:
            group.step()  # demotes the deposed leader once observed
            if victim.log is not None:
                from ..ha.replication import catch_up

                try:
                    rejoin = catch_up(
                        victim.log, group.peers_for(victim),
                        cluster_size=len(group.replicas),
                    )
                except Exception:
                    rejoin = None
            if rejoin is None:
                if _t.monotonic() > deadline:
                    raise RuntimeError("deposed replica never reconciled")
                _t.sleep(0.03)
        position = victim.log.position()
        return harness.result("region_shard_consistency", extra={
            "read_fence": read_fence,
            "teeth_shard": teeth_shard,
            "isolated_region": teeth_home,
            "deposed": old.replica_id,
            "new_leader": new.replica_id,
            "steady_shard_attempts": steady_attempts,
            "planned_homes_during_fault": {
                str(k): v for k, v in sorted(planned.items())
            },
            "rejoin": rejoin,
            "follower_position": position,
            "converged": (
                position["lastSeq"] == new.store.seq
                and position["commitSeq"] == new.store.commit_seq
            ),
        })
    finally:
        harness.stop()


def _await_migrations_settled(harness, tag: str,
                              deadline_s: float = 90.0) -> None:
    """Drive ``plane.step()`` until the migration controller reports no
    active move AND every shard satisfies the walk-completion rule. The
    step COUNT to convergence is timing-dependent (elections wait out
    lease expiry) but never enters the byte-identity artifact: the
    ``shard.migrate`` point draws no RNG while the scenario schedules
    no rules there, so extra steps leave the injection log untouched."""
    import time as _t

    deadline = _t.monotonic() + deadline_s
    while not harness.plane.migrations.settled():
        if _t.monotonic() > deadline:
            raise RuntimeError(
                f"{tag}: migration walks never settled "
                f"({harness.plane.migrations.describe()['active']})"
            )
        harness.plane.step()
        _t.sleep(0.02)


def rolling_region_outage(base_dir: str, seed: int = 31,
                          read_fence: bool = True,
                          teeth_kill: bool = False) -> dict:
    """The self-driving migration campaign (docs/sharding.md): a
    2-shard plane with ``--auto-migrate`` semantics rolls through TWO
    region outages, and the joint-consensus walk carries each shard's
    quorum out of every dark region while ack-gated writes keep
    flowing. Same checker, same artifact discipline as
    ``region_shard_consistency`` — plus the membership invariants
    (consecutive voting sets differ by one replica; consecutive
    majorities always intersect).

    Round 1 — the DARK-MINORITY-LEADER cut: shard 1 is spread (one
    replica per region) and its home-region leader is the only replica
    behind the cut. The leader steps down on quorum loss, the
    out-of-region majority elects, and ONE move (evacuate the stranded
    voter into a learner at the re-solved home) re-homes the quorum.
    The fence teeth ride along exactly as in the single-cut scenario: a
    session that observed v=3 zombie-reads the deposed leader's
    still-connected surface before the walk retires it.

    Round 2 — the DARK-MAJORITY cut: the NEW home region (now holding
    the shard's majority) is cut. A reachable leader cannot commit; the
    dark-region replica that takes over CAN (its same-region peer +
    post-cut learners), so the walk proceeds *from inside the dark
    region* and retires the dark leader last — the availability clause.
    The proof is a plain BLOCKING front-door write: it retries
    (stepping the plane, hence the walk) until the walk lands
    leadership back in a reachable region and the write acks clean.

    Hysteresis teeth on every heal: placement re-solves with
    ``stickiness_ms`` discounting the incumbent home, so healing a
    region must trigger ZERO new moves — asserted by comparing
    migration-history length across each heal.

    ``teeth_kill=True`` hard-kills the walking leader mid-move in
    round 1 (learner added, victim not yet retired). The term fence
    aborts the move on the next observed leader, the unwind retires
    the learner (never a ghost voter), and — after the heal restores a
    committable quorum — a fresh walk completes and the checker stays
    green."""
    harness = ShardedHarness(
        base_dir, seed=seed, read_fence=read_fence, spread_shards=(1,),
        auto_migrate=True, placement_stickiness_ms=100.0,
        migration_hysteresis_steps=2,
    )
    import time as _t

    try:
        plane = harness.plane
        teeth_shard, steady_shard = 1, 0
        first_home = plane.map.homes[teeth_shard]
        if first_home == plane.topology.front_door_region:
            raise RuntimeError(
                "seed places the teeth shard in the front-door region; "
                "pick another seed"
            )
        # Phase 1: baseline on both shards + cross-shard reads.
        for shard in (steady_shard, teeth_shard):
            for i in range(2):
                harness.write(
                    "writer",
                    plane.map.key_for_shard(shard, i, prefix="led"),
                )
            harness.write("writer", harness.registers[shard],
                          labels={"v": "1"})
            harness.write("writer", harness.registers[shard],
                          labels={"v": "2"}, update=True)
        harness.read_router("router-reader")
        harness.read_shard("reader", teeth_shard)

        group = plane.shard_groups[teeth_shard]
        rounds = []
        killed = None

        # ---- Round 1: cut the spread shard's home (dark minority
        # leader — the fence teeth round).
        cut1 = plane.homes[teeth_shard]
        old = group.leader()
        old_server = old.server
        planned1 = plane.isolate_region(cut1, step=1)
        warn_name = plane.map.key_for_shard(teeth_shard, 9, prefix="warn")
        warn_op = harness.recorder.invoke(
            "writer", "write", f"default/{warn_name}",
        )
        status, _payload, headers = _http_call(
            group.address, "POST", _API_JOBSETS,
            _suspended_gang_yaml(warn_name),
        )
        term, replica = _replication_identity(headers)
        harness.recorder.complete(
            warn_op, status is not None and 200 <= (status or 0) < 300,
            status=status, term=term, replica=replica,
            acked=bool(status and 200 <= status < 300
                       and not _header(headers, "Warning")),
        )
        harness.await_lost_quorum(old)
        new = harness.await_leader(teeth_shard, other_than=old)
        harness.write("writer", harness.registers[teeth_shard],
                      labels={"v": "3"}, update=True)
        harness.read_shard("reader", teeth_shard)
        # THE zombie read: before any plane.step() can retire the
        # deposed leader, a session that saw v=3 asks its surface.
        harness.read_shard("reader", teeth_shard, server=old_server)

        if teeth_kill:
            # Drive the walk to its mid-step (learner added, victim
            # still a voter) and crash the walking leader. The fence
            # must abort-unwind the move; the cut + the crash together
            # leave NO committable quorum until the heal.
            deadline = _t.monotonic() + 60.0
            while True:
                plane.step()
                active = plane.migrations.describe()["active"]
                move = active.get(str(teeth_shard))
                if move and move.get("learner"):
                    break
                if _t.monotonic() > deadline:
                    raise RuntimeError("walk never reached its mid-step")
                _t.sleep(0.02)
            killed = group.kill_leader()
        else:
            # Live writes ride through the walk: two while it runs, two
            # after it settles.
            for i in range(2, 4):
                harness.write(
                    "writer",
                    plane.map.key_for_shard(teeth_shard, i, prefix="led"),
                )
            _await_migrations_settled(harness, "round1")
            for i in range(4, 6):
                harness.write(
                    "writer",
                    plane.map.key_for_shard(teeth_shard, i, prefix="led"),
                )
            voter_regions = {
                r.replica_id: plane.replica_region.get(r.replica_id)
                for r in group.replicas
            }
            if cut1 in voter_regions.values():
                raise RuntimeError(
                    f"round 1 left a voter in the dark region: "
                    f"{voter_regions}"
                )
        history_before_heal = len(
            plane.migrations.describe()["history"]
        )
        plane.heal_region(cut1, step=2)
        _await_migrations_settled(harness, "heal1")
        rounds.append({
            "cut": cut1,
            "home_after": plane.homes[teeth_shard],
            "moves_on_heal": (
                len(plane.migrations.describe()["history"])
                - history_before_heal
            ),
        })

        if not teeth_kill:
            # ---- Round 2: cut the NEW home (dark majority — the
            # availability round).
            cut2 = plane.homes[teeth_shard]
            old2 = group.leader()
            plane.isolate_region(cut2, step=3)
            warn2 = plane.map.key_for_shard(teeth_shard, 8, prefix="warn")
            warn_op2 = harness.recorder.invoke(
                "writer", "write", f"default/{warn2}",
            )
            status2, _payload2, headers2 = _http_call(
                group.address, "POST", _API_JOBSETS,
                _suspended_gang_yaml(warn2),
            )
            term2, replica2 = _replication_identity(headers2)
            harness.recorder.complete(
                warn_op2,
                status2 is not None and 200 <= (status2 or 0) < 300,
                status=status2, term=term2, replica=replica2,
                acked=bool(status2 and 200 <= status2 < 300
                           and not _header(headers2, "Warning")),
            )
            harness.await_lost_quorum(old2)
            harness.await_leader(teeth_shard, other_than=old2)
            # THE availability proof: a blocking front-door write. Its
            # retry loop steps the plane — driving the walk out of the
            # dark region — and returns only on a CLEAN majority ack,
            # which requires leadership back in a reachable region.
            blocking_status, blocking_attempts = harness.write(
                "writer",
                plane.map.key_for_shard(teeth_shard, 6, prefix="led"),
            )
            _await_migrations_settled(harness, "round2")
            harness.write("writer", harness.registers[teeth_shard],
                          labels={"v": "4"}, update=True)
            steady_attempts = []
            for i in range(2, 4):
                _s, attempts = harness.write(
                    "writer",
                    plane.map.key_for_shard(steady_shard, i, prefix="led"),
                )
                steady_attempts.append(attempts)
            history_before_heal = len(
                plane.migrations.describe()["history"]
            )
            plane.heal_region(cut2, step=4)
            _await_migrations_settled(harness, "heal2")
            rounds.append({
                "cut": cut2,
                "home_after": plane.homes[teeth_shard],
                "moves_on_heal": (
                    len(plane.migrations.describe()["history"])
                    - history_before_heal
                ),
            })
            harness.read_shard("reader", teeth_shard)
            harness.read_router("router-reader")
        else:
            blocking_status, blocking_attempts = None, None
            steady_attempts = []
            # Post-heal, post-kill: the walk must have restarted and
            # re-homed the shard despite the crashed voter.
            harness.write("writer", harness.registers[teeth_shard],
                          labels={"v": "4"}, update=True)
            harness.read_shard("reader", teeth_shard)

        migrations = plane.migrations.describe()
        ghost_learners = [r.replica_id for r in group.learners]
        return harness.result("rolling_region_outage", extra={
            "read_fence": read_fence,
            "teeth_kill": teeth_kill,
            "teeth_shard": teeth_shard,
            "rounds": rounds,
            "deposed": old.replica_id,
            "new_leader": new.replica_id,
            "killed": killed,
            "planned_homes_round1": {
                str(k): v for k, v in sorted(planned1.items())
            },
            "blocking_write": {
                "status": blocking_status,
                "attempts": blocking_attempts,
            },
            "steady_shard_attempts": steady_attempts,
            "migrations": migrations,
            "ghost_learners": ghost_learners,
            "retired": sorted(
                r.replica_id for r in group.retired
            ),
        })
    finally:
        harness.stop()
