"""Cluster-side chaos scenarios: deterministic pod crash bursts and node
drains driven through the simulation kernel's own fault helpers.

These are the `cluster.*` injection points of the chaos plane. Unlike the
apiserver/solver points — which sit inline on real request paths — cluster
faults are *applied* by calling one of these helpers between pump rounds,
the way the failure-recovery bench applies `fail_node`. The injector still
owns every random choice (which pods crash, which node drains), so a
seeded run selects identical victims every time.
"""

from __future__ import annotations

from typing import Optional

from .injector import (
    FaultInjector,
    KIND_CORRUPT,
    KIND_CRASH,
    KIND_DRAIN,
    KIND_ENOSPC,
    KIND_EVICT,
    KIND_TORN,
)

# Pod phases considered "live" for victim selection (mirrors
# core/objects.py constants without importing the whole core package at
# module load).
_LIVE_PHASES = ("Pending", "Running")


def pod_crash_burst(
    cluster,
    injector: FaultInjector,
    rate: Optional[float] = None,
    detail: str = "",
) -> list[str]:
    """Crash a deterministic subset of live pods (container-crash analog).

    Every live pod is one arrival at the ``cluster.pod`` point, visited in
    sorted (namespace, name) order so the victim set is a pure function of
    the seed and the pod population. With ``rate`` given, a transient rule
    at that rate is installed for exactly this sweep; otherwise whatever
    ``cluster.pod`` rules the injector already carries decide.

    Returns the crashed pod names. The owning jobs observe the failures on
    the next pump round exactly like real crashes (backoffLimit accounting,
    failure policy, gang restart).
    """
    rule = None
    if rate is not None:
        rule = injector.add_rule("cluster.pod", KIND_CRASH, rate=rate)
    crashed: list[str] = []
    try:
        for key in sorted(cluster.pods):
            pod = cluster.pods.get(key)
            if pod is None or pod.status.phase not in _LIVE_PHASES:
                continue
            fault = injector.check(
                "cluster.pod", detail or f"{key[0]}/{key[1]}"
            )
            if fault is not None and fault.kind == KIND_CRASH:
                from ..api import keys as api_keys  # constants-only module

                owner = pod.labels.get(api_keys.JOBSET_NAME_KEY)
                cluster.fail_pod(*key)
                crashed.append(key[1])
                # First-class event on the owning JobSet so the injection
                # lands in its flight-recorder timeline at virtual-clock
                # time (the seq joins the injector's log).
                if owner:
                    cluster.record_event(
                        "JobSet", owner, "Warning", "ChaosPodCrash",
                        f"chaos: injected crash of pod {key[1]} "
                        f"(injection seq {fault.seq})",
                        namespace=key[0],
                    )
    finally:
        if rule is not None:
            injector.remove_rule(rule)
    return crashed


def node_drain(
    cluster,
    injector: FaultInjector,
    rate: Optional[float] = None,
) -> list[str]:
    """Drain a deterministic subset of nodes (maintenance-event analog).

    Each node is one arrival at ``cluster.node`` in sorted-name order;
    a drained node fails every live pod bound to it via the kernel's
    `fail_node` (jobs get Failed conditions -> failure policy -> gang
    recovery). Returns the drained node names.
    """
    rule = None
    if rate is not None:
        rule = injector.add_rule("cluster.node", KIND_DRAIN, rate=rate)
    drained: list[str] = []
    try:
        for name in sorted(cluster.nodes):
            fault = injector.check("cluster.node", name)
            if fault is not None and fault.kind == KIND_DRAIN:
                failed_jobs = cluster.fail_node(name)
                drained.append(name)
                # One event per drained node (kind Node, so it reaches the
                # events API / field selectors without attaching to any
                # single JobSet's timeline).
                cluster.record_event(
                    "Node", name, "Warning", "ChaosNodeDrain",
                    f"chaos: injected drain failed {len(failed_jobs)} "
                    f"job(s) (injection seq {fault.seq})",
                )
    finally:
        if rule is not None:
            injector.remove_rule(rule)
    return drained


def queue_spurious_evictions(
    cluster,
    injector: FaultInjector,
    rate: Optional[float] = None,
) -> list[str]:
    """Spuriously evict a deterministic subset of admitted gangs
    (maintenance-preemption / quota-revocation analog).

    Each admitted workload of the cluster's `QueueManager` is one arrival
    at the ``queue.admission`` point, visited in sorted (namespace, name)
    order; an ``evict`` fault re-suspends the gang and requeues it with
    backoff through the manager's own eviction path — so recovery
    (re-admission when eligible, Kueue-mutable merge on re-resume) is
    exercised exactly as a real preemption would. Returns the evicted
    JobSet names.
    """
    manager = getattr(cluster, "queue_manager", None)
    if manager is None:
        return []
    rule = None
    if rate is not None:
        rule = injector.add_rule("queue.admission", KIND_EVICT, rate=rate)
    evicted: list[str] = []
    try:
        admitted = sorted(
            (wl for wl in manager.workloads.values()
             if wl.state == "Admitted"),
            key=lambda wl: wl.key,
        )
        for wl in admitted:
            fault = injector.check(
                "queue.admission", f"{wl.key[0]}/{wl.key[1]}"
            )
            if fault is not None and fault.kind == KIND_EVICT:
                if manager.evict(
                    wl.uid, message="chaos: spurious eviction"
                ):
                    evicted.append(wl.key[1])
    finally:
        if rule is not None:
            injector.remove_rule(rule)
    return evicted


def store_torn_writes(
    data_dir: str,
    rates=(0.0, 0.1, 0.3, 0.6),
    seed: int = 11,
    writes: int = 24,
    kind: str = KIND_TORN,
) -> list[dict]:
    """Durable-store fault sweep at the ``store.write`` point: for each
    injection rate, drive a create/mutate/delete write sequence against a
    fresh cluster+store, committing after every write; a commit that hits
    an injected torn write (partial frame on disk, no fsync ack) or ENOSPC
    raises and is NOT acknowledged — the tail is repaired and the diff
    retries on the next commit, exactly as the server's commit path does.
    After the last write the store is hard-killed (abandoned, never closed
    or flushed) and recovered into a fresh cluster.

    The invariant each rate's result carries: every object covered by the
    last fsync-ACKNOWLEDGED commit is recovered byte-identically
    (``lost`` / ``mismatched`` are object counts — the caller asserts
    zero). Faults are deterministic per (seed, arrival), so a sweep is
    reproducible.
    """
    import os

    from ..core import make_cluster
    from ..store import Store, StoreError
    from ..testing import make_jobset, make_replicated_job

    results: list[dict] = []
    for i, rate in enumerate(rates):
        rate_dir = os.path.join(data_dir, f"{kind}-{i}")
        injector = FaultInjector(seed=seed)
        if rate > 0:
            injector.add_rule("store.write", kind, rate=rate)
        cluster = make_cluster()
        store = Store(rate_dir, snapshot_interval=10**9, injector=injector)
        store.recover(cluster)

        acked = failed = 0
        durable: dict = {}  # last fsync-acknowledged serialized state
        for w in range(writes):
            if w % 4 == 3:
                cluster.delete_jobset("default", f"wl-{w - 3}")
            else:
                cluster.create_jobset(
                    make_jobset(f"wl-{w}")
                    .replicated_job(
                        make_replicated_job("w").replicas(1)
                        .parallelism(1).completions(1).obj()
                    )
                    .suspend(True)
                    .obj()
                )
            cluster.run_until_stable()
            try:
                if store.commit() is not None:
                    acked += 1
                durable = store.serialized_state()
            except StoreError:
                failed += 1
                store.repair()

        # Hard-kill (no flush, no tail repair — per-record fsync is the
        # only durability), then cold-start recover.
        store.hard_kill()
        fresh = make_cluster()
        recovered_store = Store(rate_dir)
        recovered_store.recover(fresh)
        recovered = recovered_store.serialized_state()
        recovered_store.close()

        lost = mismatched = 0
        for obj_kind, objs in durable.items():
            for key, serialized in objs.items():
                got = recovered.get(obj_kind, {}).get(key)
                if got is None:
                    lost += 1
                elif got != serialized:
                    mismatched += 1
        results.append({
            "kind": kind,
            "rate": rate,
            "writes": writes,
            "commits_acked": acked,
            "commits_failed": failed,
            "faults_injected": injector.injected_total("store.write"),
            "lost": lost,
            "mismatched": mismatched,
            "recovered_objects": sum(len(v) for v in recovered.values()),
        })
    return results


def store_enospc_writes(data_dir: str, **kwargs) -> list[dict]:
    """ENOSPC variant of `store_torn_writes` (append fails before any byte
    lands; the log needs no truncation but the commit is still unacked)."""
    kwargs.setdefault("kind", KIND_ENOSPC)
    return store_torn_writes(data_dir, **kwargs)


def policy_inference_faults(
    checkpoint_path: Optional[str],
    rates=(0.0, 0.25, 1.0),
    seed: int = 11,
    jobsets: int = 6,
    replicas: int = 2,
    pods_per_job: int = 2,
    domains: int = 8,
    nodes_per_domain: int = 2,
    kind: str = KIND_CORRUPT,
    crash_rate: float = 0.4,
    score_backend: str = "numpy",
) -> list[dict]:
    """Learned-placement fault sweep at the ``policy.inference`` point:
    for each injection rate, drive a fresh cluster with ACTIVE-mode
    `LearnedPlacement` (both placement gates on) through creation, a
    seeded pod-crash burst, and gang recovery, while every learned
    inference is one arrival at the point — a ``corrupt`` fault sends
    that gang to the auction solver fallback (counted: fallbacks ==
    faults). A ``latency`` fault only DELAYS the decision — consult()
    absorbs it — so latency sweeps keep decisions learned and bank
    ``fallbacks == 0``.

    The invariant each rate's result carries (the caller asserts):
    ``unplaced_gangs == 0`` and ``double_booked_domains == 0`` at EVERY
    rate — a sick model may cost optimality, never placement.
    """
    from ..core import features, make_cluster, metrics
    from ..policy.placer import LearnedPlacement
    from ..testing import make_jobset, make_replicated_job

    topology_key = "tpu-slice"
    results: list[dict] = []
    for i, rate in enumerate(rates):
        injector = FaultInjector(seed=seed)
        if rate > 0:
            injector.add_rule("policy.inference", kind, rate=rate)
        placement = LearnedPlacement(
            checkpoint_path=checkpoint_path,
            mode="active",
            injector=injector,
            score_backend=score_backend,
        )
        fallbacks0 = metrics.policy_fallbacks_total.total()
        decisions0 = metrics.policy_decisions_total.value("active")
        with features.gate("TPUPlacementSolver", True), \
                features.gate("TPULearnedPlacer", True):
            cluster = make_cluster(placement=placement)
            cluster.add_topology(
                topology_key, num_domains=domains,
                nodes_per_domain=nodes_per_domain, capacity=8,
            )
            from ..api import FailurePolicy

            for j in range(jobsets):
                cluster.create_jobset(
                    make_jobset(f"pol-{i}-{j}")
                    .exclusive_placement(topology_key)
                    .failure_policy(FailurePolicy(max_restarts=4))
                    .replicated_job(
                        make_replicated_job("w").replicas(replicas)
                        .parallelism(pods_per_job)
                        .completions(pods_per_job).obj()
                    )
                    .obj()
                )
            cluster.run_until_stable()
            crashed = pod_crash_burst(cluster, injector, rate=crash_rate)
            cluster.run_until_stable()

        expected_pods = jobsets * replicas * pods_per_job
        bound = [p for p in cluster.pods.values() if p.spec.node_name]
        # A gang is stranded when a LIVE pod never got a node; leftover
        # Failed pod objects from the crash burst are not placements.
        unplaced = set()
        for pod in cluster.pods.values():
            if pod.status.phase in _LIVE_PHASES and not pod.spec.node_name:
                unplaced.add(pod.metadata.name.rsplit("-w-", 1)[0])
        per_domain: dict[str, set] = {}
        from ..api import keys as api_keys

        for pod in bound:
            node = cluster.nodes[pod.spec.node_name]
            per_domain.setdefault(
                node.labels[topology_key], set()
            ).add(pod.labels[api_keys.JOB_KEY])
        results.append({
            "rate": rate,
            "kind": kind,
            "gangs": jobsets,
            "pods_bound": len(bound),
            "pods_expected": expected_pods,
            "crashed_pods": len(crashed),
            "faults_injected": injector.injected_total("policy.inference"),
            "fallbacks": metrics.policy_fallbacks_total.total() - fallbacks0,
            "decisions_active": metrics.policy_decisions_total.value("active")
            - decisions0,
            "unplaced_gangs": len(unplaced),
            "double_booked_domains": sum(
                1 for ks in per_domain.values() if len(ks) > 1
            ),
        })
    return results


# ---------------------------------------------------------------------------
# Replicated-control-plane scenarios (jobset_tpu/ha, docs/ha.md)
# ---------------------------------------------------------------------------


def ha_write_attempt(address: str, name: str, timeout: float = 5.0):
    """One suspended-JobSet create against a replicated control plane's
    serving address. Returns (status, warning): a 201 with warning=None
    is a MAJORITY-acknowledged write (the contract the HA soaks and
    `bench.py --ha` both assert on — shared here so they cannot drift);
    (None, None) means no listener / connection died mid-flight."""
    import urllib.error
    import urllib.request

    from ..api import serialization
    from ..testing import make_jobset, make_replicated_job

    js = (
        make_jobset(name)
        .replicated_job(
            make_replicated_job("w").replicas(1)
            .parallelism(1).completions(1).obj()
        )
        .suspend(True)
        .obj()
    )
    req = urllib.request.Request(
        f"http://{address}/apis/jobset.x-k8s.io/v1alpha2"
        f"/namespaces/default/jobsets",
        data=serialization.to_yaml(js).encode(),
        method="POST",
        headers={"Content-Type": "application/yaml"},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, resp.headers.get("Warning")
    except urllib.error.HTTPError as exc:
        exc.read()
        return exc.code, None
    except (urllib.error.URLError, OSError):
        return None, None


def _ha_write_storm(replica_set, writes: int, kill_after: Optional[int],
                    kill, clock=None, start: int = 0) -> dict:
    """Sequential suspended-JobSet creates against the replica set's
    serving address, retrying through failovers. `kill(replica_set)` fires
    after the `kill_after`-th CLEAN acknowledgement (a 2xx without a
    Warning header — the majority-acknowledged contract). Sequential,
    ack-gated writes keep every uid/resourceVersion assignment — and
    every per-point chaos arrival — a pure function of the write index,
    which is what makes two seeded runs byte-identical."""
    import time as _t

    def attempt(name: str):
        return ha_write_attempt(replica_set.address, name)

    acked: list[str] = []
    killed = None
    unavailable_s = 0.0
    retries = 0
    for i in range(start, start + writes):
        name = f"ha-{i:03d}"
        outage_started = None
        while True:
            status, warning = attempt(name)
            if status == 201 and warning is None:
                acked.append(name)
                break
            if status == 409:
                # A retried create that actually landed before the ack was
                # lost: it exists on the serving leader; the NEXT write's
                # clean ack (same commit stream) covers its durability.
                break
            retries += 1
            if outage_started is None:
                outage_started = _t.monotonic()
            replica_set.step()
            if clock is not None:
                clock.advance(replica_set.replicas[0].elector.retry_period)
            _t.sleep(0.02)
        if outage_started is not None:
            unavailable_s += _t.monotonic() - outage_started
        if (
            kill_after is not None
            and (i - start) + 1 == kill_after
            and killed is None
        ):
            killed = kill(replica_set)
    return {
        "acked": acked,
        "killed": killed,
        "retries": retries,
        "unavailable_s": round(unavailable_s, 3),
    }


def leader_kill(
    base_dir: str,
    writes: int = 18,
    kill_after: int = 8,
    replicas: int = 3,
    seed: int = 7,
    stream_latency_rate: float = 0.25,
    stream_latency_ms: float = 1.0,
    kill: bool = True,
) -> dict:
    """Seeded leader-kill storm (the HA acceptance scenario): 3 in-process
    replicas, sequential write storm, the leader hard-killed mid-storm
    after `kill_after` majority-acknowledged writes; a follower waits out
    the lease, catches up, replays the committed log, and takes over the
    serving port. `replication.stream` latency faults ride along at
    `stream_latency_rate` so the ship path is exercised under jitter
    without perturbing quorum arithmetic.

    Returns the acked-write list, the final serialized store state of the
    surviving leader, and the injector's log — a run with `kill=False` is
    the no-kill baseline the caller asserts byte-identity against (zero
    majority-acknowledged JobSets lost)."""
    from ..ha import ReplicaSet

    injector = FaultInjector(seed=seed)
    if stream_latency_rate > 0:
        from .injector import KIND_LATENCY

        injector.add_rule(
            "replication.stream", KIND_LATENCY,
            rate=stream_latency_rate, delay_s=stream_latency_ms / 1000.0,
        )
    replica_set = ReplicaSet(
        base_dir, n=replicas,
        lease_duration=0.5, retry_period=0.1, tick_interval=0.05,
        injector=injector,
    ).start()
    try:
        result = _ha_write_storm(
            replica_set, writes,
            kill_after if kill else None,
            lambda rs: rs.kill_leader(),
        )
        leader = replica_set.leader()
        result.update({
            "scenario": "leader_kill",
            "writes": writes,
            "replicas": replicas,
            "seed": seed,
            "leader": leader.replica_id,
            "final_state": leader.store.serialized_state(),
            "final_seq": leader.store.seq,
            "commit_seq": leader.store.commit_seq,
            "resource_version": leader.store.resource_version,
            "injection_log": injector.log_snapshot(),
        })
        return result
    finally:
        replica_set.stop()


# ---------------------------------------------------------------------------
# Flow-control scenarios (jobset_tpu/flow, docs/flow.md)
# ---------------------------------------------------------------------------

# Storm-sized priority levels for `thundering_herd`: tiny seat pools so a
# sequential driver saturates them with `FlowController.hold`, and ZERO
# queue-wait budgets so a parked arrival sheds instantly instead of
# sleeping — the whole storm runs in virtual time. workload-low carries
# no queues at all (saturation sheds), workload-high keeps small sharded
# queues (its sheds are wait-budget timeouts), and the single watch seat
# forces the thread-free partial-batch path.
def _herd_levels():
    from ..flow import PriorityLevel

    return (
        PriorityLevel("exempt", seats=0),
        PriorityLevel("system", seats=4, queues=2, queue_length=8,
                      queue_wait_s=0.0),
        PriorityLevel("workload-high", seats=2, queues=2, queue_length=2,
                      queue_wait_s=0.0),
        PriorityLevel("workload-low", seats=2, queues=0),
        PriorityLevel("watch", seats=1),
    )


def thundering_herd(
    arrivals: int = 240,
    tenants: int = 6,
    seed: int = 23,
    latency_fault_rate: float = 0.1,
) -> dict:
    """Seeded overload storm against a flow-controlled controller server
    (the flow plane's acceptance scenario, driven by ``bench.py
    --overload``'s deterministic sibling and the flow tests).

    A sequential driver — every arrival completes before the next, so
    the run is a pure function of the seed — fires a mixed multi-tenant
    request storm through ``ControllerServer._route`` while
    ``FlowController.hold`` keeps the workload/watch seat pools
    saturated (the stand-in for a real concurrent herd):

    * phase ``storm``: low-priority creates shed 429 (no queues:
      ``saturated``), high-priority creates shed 429 at the zero wait
      budget (``timeout``) until one held seat is released mid-storm —
      after which high traffic lands while low traffic keeps shedding
      (the fairness split); watches answer immediate partial batches
      with retry hints; ``/debug/health`` (exempt) always executes.
    * phase ``recover``: every hold is released and the tail of the
      storm lands clean.

    ``apiserver.request`` latency faults (zero-delay, so the log records
    arrivals without costing wall time) ride along at
    ``latency_fault_rate`` — they only see requests that SURVIVED
    admission, pinning the shed-before-everything contract into the
    injection log.

    Returns the flow decision log, the injector's injection log, and the
    final cluster state — all deterministic: two runs with the same seed
    are byte-identical (``tests/test_flow.py`` asserts it), and no
    429'd create may leave an object behind (``leaked_shed_objects``
    must come back empty).
    """
    import random

    from ..api import serialization
    from ..core import make_cluster
    from ..flow import FlowController
    from ..server import ControllerServer
    from ..testing import make_jobset, make_replicated_job
    from ..utils.clock import FakeClock
    from .injector import KIND_LATENCY

    injector = FaultInjector(seed=seed)
    if latency_fault_rate > 0:
        injector.add_rule(
            "apiserver.request", KIND_LATENCY,
            rate=latency_fault_rate, delay_s=0.0,
        )
    flow = FlowController(levels=_herd_levels(), seed=seed)
    cluster = make_cluster(clock=FakeClock())
    # Never started: requests are driven straight through _route (no
    # handler threads, no pump — the arrival order IS the program order).
    server = ControllerServer(
        cluster=cluster, tick_interval=3600.0,
        injector=injector, flow=flow,
    )
    api = f"{server.API_PREFIX}/namespaces/default/jobsets"
    rng = random.Random(seed)

    def jobset_body(name: str, priority) -> bytes:
        js = (
            make_jobset(name)
            .replicated_job(
                make_replicated_job("w").replicas(1)
                .parallelism(1).completions(1).obj()
            )
            .suspend(True)
            .obj()
        )
        if priority is not None:
            js.spec.priority = priority
        return serialization.to_yaml(js).encode()

    statuses: dict[str, dict[int, int]] = {}
    shed_creates: list[str] = []
    acked_creates: list[str] = []
    n = 0

    def drive(phase: str) -> None:
        nonlocal n
        n += 1
        tenant = rng.randrange(tenants)
        op = rng.choices(
            ("create-low", "create-high", "list", "watch", "health"),
            weights=(5, 2, 2, 1, 1),
        )[0]
        headers = {"user-agent": f"herd-tenant-{tenant}"}
        if op == "create-low":
            name = f"herd-{n:04d}"
            result = server._route(
                "POST", api, jobset_body(name, None), headers=headers
            )
        elif op == "create-high":
            name = f"herd-{n:04d}"
            result = server._route(
                "POST", api, jobset_body(name, 120), headers=headers
            )
        elif op == "list":
            result = server._route("GET", api, b"", headers=headers)
        elif op == "watch":
            result = server._route(
                "GET", f"{api}?watch=1&resourceVersion=0&timeoutSeconds=0",
                b"", headers=headers,
            )
        else:
            result = server._route(
                "GET", "/debug/health", b"", headers=headers
            )
        status = result[0]
        per = statuses.setdefault(phase, {})
        per[status] = per.get(status, 0) + 1
        if op.startswith("create"):
            (acked_creates if status == 201 else shed_creates).append(name)

    try:
        held_low = flow.hold("workload-low", 2)
        held_high = flow.hold("workload-high", 2)
        held_watch = flow.hold("watch", 1)
        for i in range(arrivals):
            if i == arrivals // 2:
                # Mid-storm partial recovery: ONE high seat frees, so
                # high-priority writes start landing while low-priority
                # traffic keeps shedding — the fairness split the plane
                # exists for.
                flow.release(held_high.pop())
            drive("storm")
        for ticket in held_low + held_high + held_watch:
            flow.release(ticket)
        for _ in range(max(1, arrivals // 3)):
            drive("recover")
    finally:
        server._stop.set()
        server._httpd.server_close()

    with server.lock:
        leaked = [
            name for name in shed_creates
            if cluster.get_jobset("default", name) is not None
        ]
        final_state = {
            "resourceVersion": server._watch_rv,
            "jobsets": [
                {
                    "namespace": ns,
                    "name": name,
                    "uid": js.metadata.uid,
                    "priority": js.spec.priority,
                }
                for (ns, name), js in sorted(cluster.jobsets.items())
            ],
        }
    # Stringified statuses so the dict survives a JSON round trip
    # unchanged (byte-identity is asserted over json.dumps).
    return {
        "scenario": "thundering_herd",
        "seed": seed,
        "tenants": tenants,
        "arrivals": n,
        "statuses": {
            phase: {str(code): count for code, count in sorted(per.items())}
            for phase, per in sorted(statuses.items())
        },
        "acked_creates": len(acked_creates),
        "shed_creates": len(shed_creates),
        "leaked_shed_objects": leaked,
        "rejected_total": flow.rejected_total(),
        "flow": flow.snapshot(),
        "decision_log": flow.log_snapshot(),
        "injection_log": injector.log_snapshot(),
        "final_state": final_state,
    }


def follower_kill(
    base_dir: str,
    writes: int = 12,
    kill_after: int = 4,
    rejoin_after: int = 8,
    replicas: int = 3,
    seed: int = 7,
) -> dict:
    """Follower-loss storm: a follower is hard-killed mid-storm — the
    leader keeps acknowledging (quorum is leader + the surviving
    follower) — then rejoins and must catch up to the exact log. Returns
    write availability plus the rejoined replica's reconciliation stats
    (the caller asserts position convergence and zero failed acks)."""
    from ..ha import ReplicaSet

    injector = FaultInjector(seed=seed)
    replica_set = ReplicaSet(
        base_dir, n=replicas,
        lease_duration=0.5, retry_period=0.1, tick_interval=0.05,
        injector=injector,
    ).start()
    try:
        killed: list[str] = []
        rejoin_stats: dict = {}

        acked: list[str] = []
        for i in range(writes):
            result = _ha_write_storm(
                replica_set, 1, None, lambda rs: None, start=i,
            )
            acked.extend(result["acked"])
            if i + 1 == kill_after:
                killed.append(replica_set.kill_follower())
            if i + 1 == rejoin_after and killed:
                rejoin_stats = replica_set.rejoin(killed[0])
        leader = replica_set.leader()
        victim = next(
            r for r in replica_set.replicas
            if r.replica_id == killed[0]
        )
        return {
            "scenario": "follower_kill",
            "writes": writes,
            "killed": killed[0] if killed else None,
            "acked": len(acked),
            "rejoin": rejoin_stats,
            "leader_seq": leader.store.seq,
            "follower_position": victim.log.position(),
            "injection_log": injector.log_snapshot(),
        }
    finally:
        replica_set.stop()
