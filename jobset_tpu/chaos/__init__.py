"""Chaos plane: deterministic fault injection for resilience proofs.

`injector` holds the seeded rule engine and the process-global accessor
(the CLI's ``--inject`` installs one; instrumented boundaries consult it);
`scenarios` drives cluster-side faults (pod crash bursts, node drains)
through the simulation kernel. See ``docs/troubleshooting.md`` §
"Degradation modes" for how the hardened paths behave under these faults.
"""

from .injector import (
    Fault,
    FaultInjector,
    KIND_BREAK,
    KIND_CORRUPT,
    KIND_CRASH,
    KIND_DRAIN,
    KIND_ENOSPC,
    KIND_ERROR,
    KIND_EVICT,
    KIND_LATENCY,
    KIND_REFUSE,
    KIND_SLOW,
    KIND_TORN,
    Rule,
    configure,
    consult,
    disable,
    get_injector,
)
from .scenarios import (
    node_drain,
    pod_crash_burst,
    policy_inference_faults,
    queue_spurious_evictions,
    store_enospc_writes,
    store_torn_writes,
)

__all__ = [
    "Fault",
    "FaultInjector",
    "KIND_BREAK",
    "KIND_CORRUPT",
    "KIND_CRASH",
    "KIND_DRAIN",
    "KIND_ENOSPC",
    "KIND_ERROR",
    "KIND_EVICT",
    "KIND_LATENCY",
    "KIND_REFUSE",
    "KIND_SLOW",
    "KIND_TORN",
    "Rule",
    "configure",
    "consult",
    "disable",
    "get_injector",
    "node_drain",
    "pod_crash_burst",
    "policy_inference_faults",
    "queue_spurious_evictions",
    "store_enospc_writes",
    "store_torn_writes",
]
