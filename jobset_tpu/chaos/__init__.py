"""Chaos plane: deterministic fault injection for resilience proofs.

`injector` holds the seeded rule engine and the process-global accessor
(the CLI's ``--inject`` installs one; instrumented boundaries consult it);
`net` models per-link network partitions (seeded ``PartitionPlan`` of
directed cuts/heals, enforced at the HA peer transports and the client);
`scenarios` drives cluster-side faults (pod crash bursts, node drains)
through the simulation kernel. See ``docs/troubleshooting.md`` §
"Degradation modes" for how the hardened paths behave under these faults.
"""

from .injector import (
    Fault,
    FaultInjector,
    KIND_BREAK,
    KIND_CORRUPT,
    KIND_CRASH,
    KIND_DRAIN,
    KIND_ENOSPC,
    KIND_ERROR,
    KIND_EVICT,
    KIND_LATENCY,
    KIND_REFUSE,
    KIND_SLOW,
    KIND_TORN,
    Rule,
    configure,
    consult,
    disable,
    get_injector,
)
from .net import PartitionPlan
from .scenarios import (
    asymmetric_link,
    leader_isolated,
    node_drain,
    partition_flap,
    pod_crash_burst,
    policy_inference_faults,
    queue_spurious_evictions,
    split_3way,
    store_enospc_writes,
    store_torn_writes,
)

__all__ = [
    "Fault",
    "FaultInjector",
    "KIND_BREAK",
    "KIND_CORRUPT",
    "KIND_CRASH",
    "KIND_DRAIN",
    "KIND_ENOSPC",
    "KIND_ERROR",
    "KIND_EVICT",
    "KIND_LATENCY",
    "KIND_REFUSE",
    "KIND_SLOW",
    "KIND_TORN",
    "PartitionPlan",
    "Rule",
    "configure",
    "consult",
    "disable",
    "get_injector",
    "asymmetric_link",
    "leader_isolated",
    "node_drain",
    "partition_flap",
    "pod_crash_burst",
    "policy_inference_faults",
    "queue_spurious_evictions",
    "split_3way",
    "store_enospc_writes",
    "store_torn_writes",
]
