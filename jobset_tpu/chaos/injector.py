"""Deterministic, seedable fault injector — the chaos plane's core.

The control plane's whole value proposition is gang lifecycle *under
failure*, yet nothing in the build proved recovery behavior at the process
boundaries (apiserver HTTP, solver gRPC stream, cluster nodes/pods) until
this module existed. It provides named **injection points** that the real
code paths consult, and **rules** that decide — deterministically, from a
seed — whether a given arrival at a point suffers a fault.

Design constraints:

1. **Deterministic.** Every injection point owns its own
   ``random.Random`` seeded from ``(seed, point)``, so the decision stream
   at one point is a pure function of (seed, arrival index at that point)
   — interleavings *across* points (e.g. a solver solve between two
   apiserver requests) cannot perturb each other's draws. Two runs that
   present the same per-point arrival sequences produce byte-identical
   injection logs; ``tests/test_chaos.py`` asserts this.
2. **Near-zero cost when off.** The module-level accessor returns ``None``
   when chaos is unconfigured; call sites guard with one attribute read.
   No rule registered at a point means no RNG draw for arrivals there.
3. **Observable.** Every injected fault lands in a bounded in-memory log
   (seq, point, arrival index, fault kind, detail) and bumps the
   ``jobset_chaos_injected_faults_total`` counter, so a soak run can prove
   both that faults actually fired and that two seeded runs fired
   identically.

Injection points used by the build (callers may invent more — points are
just names):

================== ======================================================
``apiserver.request``  controller HTTP handler: error codes + added latency
``solver.connect``     gRPC channel dial: connect refusal
``solver.stream``      solver bidi stream: mid-stream breaks, slow frames
``cluster.pod``        simulated kubelet: pod crash bursts
``cluster.node``       simulated cloud: node drain
``queue.admission``    gang admission plane: admit-latency, spurious evict
``store.write``        durable-store WAL append: fsync latency, torn-tail
                       truncation, ENOSPC (also consulted per lease-file
                       write, so an unwritable shared volume is testable)
``replication.stream`` HA leader->follower WAL frame shipping: stream
                       break (frame dropped pre-flight, follower lags and
                       is caught up from the resend buffer), added
                       latency
``policy.inference``   learned-placement scoring (active mode): added
                       latency, or ``corrupt`` — the model is treated as
                       unusable for that decision and placement falls
                       back to the auction solver
``net.partition``      per-link network fault model (chaos/net.py): a
                       directed (src, dst) link cut blackholes/refuses
                       delivery at both transports (HA peer RPCs in
                       ha/replication.py, client requests in client.py).
                       Spec rules here fire per delivery (``refuse``);
                       a seeded ``PartitionPlan``'s scheduled cut AND
                       heal transitions land in this log as first-class
                       entries, so seeded-run byte-identity covers
                       recovery timing, not just fault onsets
``shard.route``        sharded front door (shard/router.py): one arrival
                       per dispatch to an owning shard's leader — any
                       error kind makes that dispatch answer
                       503 + shard-leader hint (the unroutable path, as
                       if the shard were dark), ``latency`` delays it
``shard.migrate``      migration controller (shard/migrate.py): one
                       arrival per controller step of an ACTIVE
                       joint-consensus move — ``stall`` holds the walk
                       a step, ``break`` fails the current learner-sync
                       attempt (retried next step, bounded by the sync
                       budget), ``abort`` (or any other error kind)
                       triggers the abort-unwind back to the pre-move
                       membership
================== ======================================================

Spec grammar (CLI ``--inject`` / ``FaultInjector.from_spec``)::

    spec    := clause (";" clause)*
    clause  := point ":" kind ["," arg]* "@" rate
    arg     := key "=" value        (status=503, ms=20, times=4)

Examples::

    apiserver.request:error,status=503@0.05
    apiserver.request:latency,ms=20@0.1
    solver.stream:break@0.02;solver.connect:refuse@1.0
"""

from __future__ import annotations

import random
import threading
from dataclasses import dataclass, field
from typing import Optional

# Fault kinds understood by the shipped call sites. Points/kinds are open
# vocabulary — the injector matches strings, the call site interprets them.
KIND_ERROR = "error"      # apiserver: answer `status` instead of routing
KIND_LATENCY = "latency"  # apiserver: sleep `ms` before routing
KIND_REFUSE = "refuse"    # solver.connect: refuse the dial
KIND_BREAK = "break"      # solver.stream: break the stream mid-flight
KIND_SLOW = "slow"        # solver.stream: delay the reply frame by `ms`
KIND_CRASH = "crash"      # cluster.pod: crash the pod
KIND_DRAIN = "drain"      # cluster.node: drain the node
KIND_EVICT = "evict"      # queue.admission: spuriously evict/deny a gang
KIND_TORN = "torn"        # store.write: crash mid-append (partial frame)
KIND_ENOSPC = "enospc"    # store.write: fail the append before any byte
KIND_CORRUPT = "corrupt"  # policy.inference: checkpoint/model unusable


@dataclass
class Fault:
    """One injected fault, as returned to the call site."""

    point: str
    kind: str
    status: int = 503
    delay_s: float = 0.0
    seq: int = 0  # global injection sequence number (log join key)


@dataclass
class Rule:
    """One fault rule at one injection point.

    ``rate`` is the per-arrival injection probability; ``times`` bounds how
    many faults the rule may inject in total (0 = unlimited) — tests use
    ``times`` to script exact failure counts ("503 the first two requests,
    then recover")."""

    point: str
    kind: str
    rate: float = 1.0
    status: int = 503
    delay_s: float = 0.0
    times: int = 0
    injected: int = field(default=0, compare=False)

    def exhausted(self) -> bool:
        return self.times > 0 and self.injected >= self.times


class FaultInjector:
    """Seeded rule engine consulted by the instrumented boundaries.

    Thread-safe: the apiserver handler pool and the reconcile pump may
    consult concurrently. Determinism holds per point — each point's
    decision stream depends only on its own arrival order.
    """

    MAX_LOG = 100_000  # bounded, but big enough to diff a whole soak run

    def __init__(self, seed: int = 0):
        self.seed = seed
        self._lock = threading.Lock()
        self._rules: dict[str, list[Rule]] = {}
        self._rngs: dict[str, random.Random] = {}
        self._arrivals: dict[str, int] = {}
        self._injected_by_point: dict[str, int] = {}
        self.log: list[dict] = []
        self._seq = 0

    # -- configuration ----------------------------------------------------

    def add_rule(
        self,
        point: str,
        kind: str,
        rate: float = 1.0,
        status: int = 503,
        delay_s: float = 0.0,
        times: int = 0,
    ) -> Rule:
        rule = Rule(point=point, kind=kind, rate=rate, status=status,
                    delay_s=delay_s, times=times)
        with self._lock:
            self._rules.setdefault(point, []).append(rule)
        return rule

    def remove_rule(self, rule: Rule) -> None:
        """Unregister one rule (transient scenario rules); the point's RNG
        stream and the log remain — removal must not rewind determinism."""
        with self._lock:
            rules = self._rules.get(rule.point)
            if rules is not None:
                # Identity, not dataclass equality: two rules with the same
                # parameters must stay independently removable.
                remaining = [r for r in rules if r is not rule]
                if remaining:
                    self._rules[rule.point] = remaining
                else:
                    self._rules.pop(rule.point, None)

    def clear(self, point: Optional[str] = None) -> None:
        """Drop rules (one point, or all); the log and RNG streams remain —
        clearing mid-scenario must not rewind determinism."""
        with self._lock:
            if point is None:
                self._rules.clear()
            else:
                self._rules.pop(point, None)

    @classmethod
    def from_spec(cls, spec: str, seed: int = 0) -> "FaultInjector":
        """Build an injector from the CLI spec grammar (module docstring)."""
        injector = cls(seed=seed)
        for clause in filter(None, (c.strip() for c in spec.split(";"))):
            body, _, rate_s = clause.rpartition("@")
            if not body:
                raise ValueError(
                    f"bad chaos clause {clause!r}: missing '@rate'"
                )
            point, _, kind_args = body.partition(":")
            if not point or not kind_args:
                raise ValueError(
                    f"bad chaos clause {clause!r}: want point:kind[,k=v]@rate"
                )
            kind, *args = (a.strip() for a in kind_args.split(","))
            kwargs: dict = {"rate": float(rate_s)}
            for arg in args:
                key, _, value = arg.partition("=")
                if key == "status":
                    kwargs["status"] = int(value)
                elif key == "ms":
                    kwargs["delay_s"] = float(value) / 1000.0
                elif key == "times":
                    kwargs["times"] = int(value)
                else:
                    raise ValueError(
                        f"bad chaos arg {arg!r} in clause {clause!r}"
                    )
            injector.add_rule(point, kind, **kwargs)
        return injector

    # -- decision ---------------------------------------------------------

    def _rng_for_locked(self, point: str) -> random.Random:
        rng = self._rngs.get(point)
        if rng is None:
            # Stable derivation, independent of registration or first-use
            # order across points: (seed, point) -> stream.
            rng = random.Random(f"{self.seed}/{point}")
            self._rngs[point] = rng
        return rng

    def check(self, point: str, detail: str = "") -> Optional[Fault]:
        """One arrival at `point`: returns the injected Fault or None.

        Exactly ONE rng draw per arrival at a point with rules (drawn even
        when every rule is exhausted, so `times=`-scripted scenarios keep
        later arrivals aligned with an unscripted run). The single draw is
        partitioned across the point's rules as a categorical: rule i owns
        the interval [sum(rates[:i]), sum(rates[:i]) + rate_i), so two 5%
        rules at one point EACH fire at 5% instead of the second being
        shadowed by the first. Rates summing past 1.0 clip the tail rules.
        An exhausted rule's interval stays reserved (no fault fires in it)
        so exhaustion never shifts the other rules' streams."""
        with self._lock:
            rules = self._rules.get(point)
            if not rules:
                return None
            arrival = self._arrivals.get(point, 0)
            self._arrivals[point] = arrival + 1
            u = self._rng_for_locked(point).random()
            cum = 0.0
            hit = None
            for rule in rules:
                if cum <= u < cum + rule.rate:
                    hit = rule
                    break
                cum += rule.rate
            if hit is None or hit.exhausted():
                return None
            hit.injected += 1
            fault = self._injected_locked(
                point, hit.kind, arrival, detail,
                status=hit.status, delay_s=hit.delay_s,
            )
        # Outside the lock: metrics must not serialize the handler pool.
        from ..core import metrics

        metrics.chaos_injected_faults_total.inc(point)
        return fault

    def _injected_locked(self, point: str, kind: str, arrival: int,
                         detail: str, status: int = 503,
                         delay_s: float = 0.0) -> Fault:
        """Shared bookkeeping for a fault entering the log — rule-fired
        (check) and externally-applied (record) entries must stay
        structurally identical, the byte-identity gates compare them in
        one stream. Caller holds self._lock and bumps the metric outside
        it."""
        self._seq += 1
        self._injected_by_point[point] = (
            self._injected_by_point.get(point, 0) + 1
        )
        fault = Fault(point=point, kind=kind, status=status,
                      delay_s=delay_s, seq=self._seq)
        if len(self.log) < self.MAX_LOG:
            self.log.append({
                "seq": self._seq,
                "point": point,
                "arrival": arrival,
                "kind": kind,
                "detail": detail,
            })
        return fault

    def record(self, point: str, kind: str, detail: str = "") -> Fault:
        """First-class injection-log entry for an externally-APPLIED fault
        transition (the partition plan's scheduled cut/heal events,
        chaos/net.py): consumes NO rng draw and consults no rules —
        a scheduled transition must not perturb the point's decision
        stream — but lands in the log, the sequence numbering, and the
        counters exactly like a rule-injected fault. Heal events going
        through here is what lets seeded-run byte-identity cover recovery
        timing rather than only fault onsets."""
        with self._lock:
            arrival = self._arrivals.get(point, 0)
            self._arrivals[point] = arrival + 1
            fault = self._injected_locked(point, kind, arrival, detail)
        from ..core import metrics

        metrics.chaos_injected_faults_total.inc(point)
        return fault

    # -- introspection ----------------------------------------------------

    def injected_total(self, point: Optional[str] = None) -> int:
        """Faults injected so far (counters, not the bounded log — the
        counts stay exact past MAX_LOG truncation)."""
        with self._lock:
            if point is None:
                return self._seq
            return self._injected_by_point.get(point, 0)

    def log_snapshot(self) -> list[dict]:
        with self._lock:
            return [dict(e) for e in self.log]


# ---------------------------------------------------------------------------
# Process-global injector (what the CLI configures and the default call
# sites consult). Tests construct private injectors and pass them
# explicitly instead.
# ---------------------------------------------------------------------------

_GLOBAL: Optional[FaultInjector] = None


def configure(spec: str = "", seed: int = 0,
              injector: Optional[FaultInjector] = None) -> FaultInjector:
    """Install the process-global injector (CLI --inject path)."""
    global _GLOBAL
    _GLOBAL = injector if injector is not None else FaultInjector.from_spec(
        spec, seed=seed
    )
    return _GLOBAL


def get_injector() -> Optional[FaultInjector]:
    return _GLOBAL


def consult(point: str, detail: str = "",
            injector: Optional[FaultInjector] = None) -> Optional[Fault]:
    """One arrival at `point` with the standard call-site boilerplate
    folded in: resolve `injector` (explicit, else the process-global one),
    check, and APPLY any latency fault in place (sleep, then report no
    fault). Returns a Fault only for kinds the caller must interpret
    (error/torn/enospc/break/...), or None. Shared by the WAL append,
    lease write, and replication ship sites so fault semantics cannot
    drift between them."""
    if injector is None:
        injector = _GLOBAL
    if injector is None:
        return None
    fault = injector.check(point, detail)
    if fault is None:
        return None
    if fault.kind == KIND_LATENCY:
        if fault.delay_s > 0:
            import time as _t

            _t.sleep(fault.delay_s)
        return None
    return fault


def disable() -> None:
    global _GLOBAL
    _GLOBAL = None
