"""Autoregressive decoding for the flagship transformer (serving path).

TPU-idiomatic greedy/sampling decode: a KV cache with static `max_len`
shapes, one `lax.scan` over decode steps (no Python loop, one compiled
program), `dynamic_update_slice` cache writes, and position-masked
attention. Runs under `shard_map` on the same 5-axis mesh as training with
the serving-shaped axes active — dp for batch throughput, tp for latency
(column/row-parallel projections with one psum per layer, vocab-sharded
logits) — while pp/sp/ep must be 1 (pipeline microbatching and ring
attention are training-shape optimizations; a decode step's sequence
length is 1, so there is nothing to ring over).

The reference has no inference surface at all (it orchestrates containers);
this is the serving half of the workload plane.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.flash_block import blockwise_causal_attention
from ..parallel.mesh import axis_size, pvary_to, vma_union
from .quant import QuantizedTensor, quantize_int8, weight_cast
from .transformer import (
    TransformerConfig,
    _dense_mlp,
    _embed_tokens,
    _moe_mlp,
    param_specs,
    renormalized_topk,
    repeat_kv,
    rms_norm,
    rotary,
    unembed_logits,
)

NEG_INF = -1.0e30


def _topk_gates(p, xn, cfg: TransformerConfig):
    """Shared router stanza for both top-k serving formulations: softmax
    gates in f32 (routing stability, same as training), top-k pick,
    renormalized weights. Returns (top_w, top_i), each [B, T, k]."""
    gates = jax.nn.softmax(
        jnp.einsum(
            "btd,de->bte", xn.astype(jnp.float32), p["wg"].astype(jnp.float32)
        ),
        axis=-1,
    )
    return renormalized_topk(gates, cfg.moe_top_k)  # each [B, T, k]


def _moe_mlp_topk_decode(p, xn, cfg: TransformerConfig):
    """Token-choice top-k MoE, dense-all-experts formulation (ep == 1).

    Running every expert on every token and weighting by the top-k gates
    is a single MXU-friendly einsum chain — no capacity buffers, no
    all_to_all (there is no ep axis to ship over), and no token drops.
    This is the no-contention limit of the training path
    (`transformer._moe_mlp_routed`, reference: none — the reference has no
    inference surface): identical per-token math whenever training capacity
    admits every choice, which a serving batch trivially satisfies.
    Expert FFN weights stay column/row split over tp with one psum, exactly
    like the dense path.

    Cost note: exactness here costs E/k times the routed FFN FLOPs per
    token — negligible for the single-token decode step, whose latency is
    set by streaming ALL expert weights from HBM either way. Prefill,
    which is compute-bound, instead uses the sorted ragged formulation
    (`_moe_mlp_topk_sorted`) at activated-FLOPs cost.
    """
    compute = cfg.dtype
    top_w, top_i = _topk_gates(p, xn, cfg)
    weights = jnp.sum(
        jax.nn.one_hot(top_i, cfg.n_experts, dtype=jnp.float32)
        * top_w[..., None],
        axis=-2,
    )  # [B, T, E], nonzero only at the k chosen experts

    h = jax.nn.silu(
        jnp.einsum("btd,edf->ebtf", xn.astype(compute),
                   weight_cast(p["we1"], compute))
    )
    y = jnp.einsum("ebtf,efd->ebtd", h, weight_cast(p["we2"], compute))
    out = jnp.einsum("ebtd,bte->btd", y, weights.astype(compute))
    return lax.psum(out, "tp")


def _moe_mlp_topk_sorted(p, xn, cfg: TransformerConfig):
    """Token-choice top-k MoE for prefill: exact sorted ragged dispatch.

    The prefill pass is compute-bound, so the dense-all-experts
    formulation's E/k FLOPs overhead is real money there. This path pays
    only activated FLOPs with no drops and no capacity buffers: replicate
    each token's k (token, expert) slots, sort the slots by expert, run
    the expert FFNs as two grouped matmuls over the contiguous per-expert
    segments (`lax.ragged_dot` — the TPU-native grouped-GEMM primitive),
    and scatter-add the gate-weighted results back per token. Identical
    per-token math to the dense formulation (differential-tested); expert
    FFN weights stay column/row split over tp with one psum.
    """
    from .transformer import sorted_ragged_expert_ffn

    compute = cfg.dtype
    k = cfg.moe_top_k
    b, t, d = xn.shape
    n = b * t
    top_w, top_i = _topk_gates(p, xn, cfg)
    out, _ = sorted_ragged_expert_ffn(
        p, xn.reshape(n, d), top_w.reshape(n, k), top_i.reshape(n, k), cfg
    )
    return lax.psum(out.reshape(b, t, d).astype(compute), "tp")


def _decode_mlp(p, xn, cfg: TransformerConfig):
    """Feed-forward dispatch for serving: dense, soft-dispatch MoE, top-k
    routed MoE (sorted ragged dispatch for prefill, dense-all-experts for
    the single-token decode step — see the T > 1 branch below), or
    expert-choice.

    Expert-choice routing is not causal — at train time an expert's top-C
    choice over a token set lets earlier tokens' compute depend on later
    tokens, which an autoregressive server cannot reproduce. Serving
    therefore uses the router's FULL-CAPACITY limit (the dense soft
    dispatch, where every expert weighs every token by its gate): exact
    whenever training capacity did not bind, and the standard smooth
    approximation where it did (the EC paper serves with per-token
    approximations for the same reason)."""
    if "wg" in p and cfg.moe_router == "expert":
        return _moe_mlp(p, xn, cfg)
    if "wg" in p and cfg.moe_top_k > 0:
        # Prefill (T > 1, compute-bound): sorted ragged dispatch at
        # activated FLOPs. Single-token decode (bandwidth-bound): the
        # dense-all-experts chain — all expert weights stream from HBM
        # either way, and it avoids the sort/scatter overhead per step.
        if xn.shape[1] > 1:
            return _moe_mlp_topk_sorted(p, xn, cfg)
        return _moe_mlp_topk_decode(p, xn, cfg)
    if "wg" in p:
        return _moe_mlp(p, xn, cfg)
    return _dense_mlp(p, xn, cfg)


def init_kv_cache(
    config: TransformerConfig,
    mesh: Mesh,
    batch: int,
    max_len: int,
    quantized_kv: bool = False,
) -> dict:
    """Global KV cache arrays [layers, B, max_len, H_kv, D], head-sharded on
    tp and batch-sharded on dp. With GQA the cache holds only the
    n_kv_heads K/V heads — the full serving-memory win — and reads are
    broadcast per query-head group at compute time.

    quantized_kv: store the cache as per-vector int8 (QuantizedTensor with
    one f32 scale per [layer, batch, position, head]) — the cache is THE
    memory/bandwidth term at long context, so int8 roughly doubles
    servable context and halves the cache's share of per-token reads."""
    cfg = config
    shape = (cfg.n_layers, batch, max_len, cfg.kv_heads, cfg.head_dim)
    sharding = NamedSharding(mesh, P(None, "dp", None, "tp", None))
    if quantized_kv:
        def part():
            return QuantizedTensor(
                q=jax.device_put(jnp.zeros(shape, jnp.int8), sharding),
                # Unwritten positions dequantize to 0 (q=0) regardless of
                # scale; 1.0 keeps the math finite. Scale rank mirrors the
                # cache (size-1 vector axis) so the cache sharding applies
                # to both leaves as a pytree prefix.
                scale=jax.device_put(
                    jnp.ones((*shape[:-1], 1), jnp.float32), sharding
                ),
            )

        return {"k": part(), "v": part()}
    # Cache lives in the compute dtype (bf16 for serving configs) — it is
    # the dominant HBM term; the attention dot upcasts to f32.
    zeros = jnp.zeros(shape, cfg.dtype)
    return {
        "k": jax.device_put(zeros, sharding),
        "v": jax.device_put(zeros, sharding),
    }


def _cache_write(cache_part, value, pos: int):
    """Write `value` [B, T, H, D] into the cache at position `pos`: plain
    dtype-cast store, or per-vector int8 (scale = absmax over D / 127) for
    a quantized cache."""
    if isinstance(cache_part, QuantizedTensor):
        qt = quantize_int8(value, axis=-1)  # one scale per cached vector
        return QuantizedTensor(
            q=lax.dynamic_update_slice(cache_part.q, qt.q, (0, pos, 0, 0)),
            scale=lax.dynamic_update_slice(
                cache_part.scale, qt.scale, (0, pos, 0, 0)
            ),
        )
    return lax.dynamic_update_slice(
        cache_part, value.astype(cache_part.dtype), (0, pos, 0, 0)
    )


def _cache_read(cache_part, dtype):
    """Full cache view in the compute dtype: identity cast for plain
    (already compute-dtype) caches, fused dequantization for int8 caches
    (int8 bytes cross HBM; the convert+scale rides the attention matmul's
    operand read). Softmax statistics stay f32 at the consumer via
    preferred_element_type. One dequant definition: quant.weight_cast."""
    return weight_cast(cache_part, dtype)


def _decode_layer(p, x, cache_k, cache_v, pos, cfg: TransformerConfig):
    """One layer, one token: x [B, 1, d]; cache_k/v [B, T_max, H_loc, D].
    Returns (x, new_cache_k, new_cache_v)."""
    kv_heads_local = cache_k.shape[2]
    group = cfg.n_heads // cfg.kv_heads

    xn = rms_norm(x, p["ln1"], cfg.norm_eps)
    q, k, v = _layer_qkv(p, xn, pos, kv_heads_local, cfg)

    cache_k = _cache_write(cache_k, k, pos)
    cache_v = _cache_write(cache_v, v, pos)

    # GQA: the cache is read at its compact kv-head width and broadcast per
    # query-head group (a fused broadcast, not a copy) — bandwidth, the
    # decode bottleneck, scales with kv_heads.
    full_k = repeat_kv(_cache_read(cache_k, cfg.dtype), group)
    full_v = repeat_kv(_cache_read(cache_v, cfg.dtype), group)
    scale = cfg.head_dim ** -0.5
    # Operands stay in the compute dtype; f32 logits/softmax via the
    # accumulator — same statistics policy as the flash kernel.
    logits = (
        jnp.einsum(
            "bqhd,bkhd->bhqk", q, full_k, preferred_element_type=jnp.float32
        )
        * scale
    )  # [B,H,1,T]
    t_max = full_k.shape[1]
    visible = jax.lax.broadcasted_iota(jnp.int32, (1, 1, 1, t_max), 3) <= pos
    logits = jnp.where(visible, logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    attn = jnp.einsum(
        "bhqk,bkhd->bqhd", probs.astype(full_v.dtype), full_v,
        preferred_element_type=jnp.float32,
    )
    return _layer_tail(p, x, attn, cfg), cache_k, cache_v


def _layer_qkv(p, xn, base, kv_heads_local, cfg: TransformerConfig):
    """Shared projection stanza for prefill and decode: q/k/v for the
    tokens in xn (global positions base..base+T-1), rotary applied. K/V
    come out with the (possibly smaller, GQA) kv head count — exactly what
    the cache stores; q with the full local query head count."""
    compute = cfg.dtype
    positions = base + jnp.arange(xn.shape[1], dtype=jnp.float32)
    group = cfg.n_heads // cfg.kv_heads

    def proj(w, n_heads):
        y = jnp.einsum("btd,df->btf", xn.astype(compute), weight_cast(w, compute))
        return y.reshape(*y.shape[:-1], n_heads, cfg.head_dim)

    # q stays in the compute dtype: the attention matmuls run at that
    # dtype's MXU rate (the compute-bound prefill's dominant cost), and
    # both consumers keep their softmax statistics in f32 regardless —
    # block_attention internally, the decode step via its
    # preferred_element_type=f32 logits einsum.
    q = rotary(
        proj(p["wq"], kv_heads_local * group), positions, cfg.rope_theta
    )
    k = rotary(proj(p["wk"], kv_heads_local), positions, cfg.rope_theta)
    return q, k, proj(p["wv"], kv_heads_local)


def _layer_tail(p, x, attn, cfg: TransformerConfig):
    """Shared output-projection + MLP stanza: attn [B, T, H_loc, D]."""
    compute = cfg.dtype
    attn = attn.reshape(*attn.shape[:-2], attn.shape[-2] * attn.shape[-1])
    out = jnp.einsum(
        "btf,fd->btd", attn.astype(compute), weight_cast(p["wo"], compute)
    )
    x = x + lax.psum(out, "tp").astype(x.dtype)
    xn2 = rms_norm(x, p["ln2"], cfg.norm_eps)
    return x + _decode_mlp(p, xn2, cfg).astype(x.dtype)


def _prefill_layer(p, x, cache_k, cache_v, cfg: TransformerConfig):
    """One layer over the WHOLE prompt: x [B, Tp, d]; caches
    [B, T_max, H_loc, D]. Writes K/V for every prompt position in one
    batched pass (positions 0..Tp-1) and returns (x, cache_k, cache_v).

    Attention is the shared blockwise fold over the flash kernel: biases
    and probability tiles stay chunk-sized constants, so prompt length is
    bounded by the cache, not by any [Tp, Tp] attention scratch."""
    kv_heads_local = cache_k.shape[2]

    xn = rms_norm(x, p["ln1"], cfg.norm_eps)
    q, k, v = _layer_qkv(p, xn, 0, kv_heads_local, cfg)

    cache_k = _cache_write(cache_k, k, 0)
    cache_v = _cache_write(cache_v, v, 0)

    attn = blockwise_causal_attention(q, k, v)  # GQA broadcast inside
    return _layer_tail(p, x, attn, cfg), cache_k, cache_v


def _prefill_logits(params, prompt, cache, cfg):
    """prompt [B, Tp] -> (last-position logits [B, V_local], filled cache).

    The prompt is consumed in ONE batched causal pass per layer (MXU-shaped
    [Tp, d] matmuls and a single parameter stream) instead of Tp sequential
    cached steps — prefill is compute-bound where decode is bandwidth-bound,
    so batching it moves prompt cost from Tp weight-streams to one.
    """
    x = _embed_tokens(params["embed"], prompt, cfg)  # [B, Tp, d]
    return _run_stack(
        params, x, cache, cfg,
        lambda p, x, ck, cv: _prefill_layer(p, x, ck, cv, cfg),
    )


def _run_stack(params, x, cache, cfg, layer_fn):
    """Shared layer-scan + epilogue for prefill and decode: run `layer_fn`
    over the stacked layers (scan over layers_per_stage; pp == 1 in
    serving), final-norm the LAST position, unembed it.

    Params shard over the (size-1) pp axis, so layer outputs are typed
    pp-varying; the scan carry must enter with the same vma type.
    Returns (last-position logits [B, V_local] f32, new cache).
    """
    stage_params = jax.tree.map(lambda a: a[0], params["layers"])
    vma = vma_union(x, stage_params, cache)
    x = pvary_to(x, vma)

    def tree_pvary(t):
        return jax.tree.map(lambda a: pvary_to(a, vma), t)

    def body(carry, inputs):
        x = carry
        layer_p, ck, cv = inputs
        x, ck, cv = layer_fn(layer_p, x, ck, cv)
        return pvary_to(x, vma), (tree_pvary(ck), tree_pvary(cv))

    x, (new_k, new_v) = lax.scan(
        body, x, (stage_params, cache["k"], cache["v"])
    )
    xn = rms_norm(x[:, -1:], params["final_norm"], cfg.norm_eps)
    logits = unembed_logits(params, xn, cfg)
    return logits[:, 0].astype(jnp.float32), {"k": new_k, "v": new_v}


def _token_logits(params, token, cache, pos, cfg):
    """token [B] -> (logits [B, V_local], new cache). Runs on local shards."""
    x = _embed_tokens(params["embed"], token[:, None], cfg)  # [B, 1, d]
    return _run_stack(
        params, x, cache, cfg,
        lambda p, x, ck, cv: _decode_layer(p, x, ck, cv, pos, cfg),
    )


def _global_argmax(logits):
    """Greedy pick over the tp-sharded vocab: local argmax, then psum-max
    a (value, global index) pair across tp."""
    v_local = logits.shape[-1]
    v_start = lax.axis_index("tp") * v_local
    local_idx = jnp.argmax(logits, axis=-1)
    local_val = jnp.max(logits, axis=-1)
    global_val = lax.pmax(local_val, "tp")
    mine = local_val >= global_val  # winner shard(s)
    candidate = jnp.where(mine, v_start + local_idx, jnp.iinfo(jnp.int32).max)
    return lax.pmin(candidate.astype(jnp.int32), "tp")  # lowest-index tie-break


def _pick_token(logits, key, pos, temperature: float, top_k: int):
    """Greedy (temperature == 0) or sampled pick over the tp-sharded vocab.

    Sampling is Gumbel-max: argmax(logits/T + G) is an exact draw from
    softmax(logits/T), and the argmax is exactly the global-argmax reduction
    the greedy path already does — so sharded sampling needs no logits
    gather. Each tp shard draws independent noise for its vocab slice
    (key folded with the decode position and the shard index).

    top_k > 0 restricts sampling to EXACTLY the k globally-largest logits,
    ties broken by lowest vocab index (the conventional "first k" order):
    every shard's local top-k (values, global indices) are all-gathered
    over tp (k*tp floats+ints — trivial), a stable value-descending sort
    of the gathered candidates picks the k winners (the gathered order is
    global-index-ascending among equal values, both within a shard —
    lax.top_k puts lower indices first on ties — and across shards, so
    stability IS the index tie-break), and the mask keeps a tied-at-
    threshold logit only up to the last selected index. With bf16-cast
    params producing tied logits this still admits exactly k candidates.
    """
    if temperature <= 0.0:
        return _global_argmax(logits)
    z = logits.astype(jnp.float32) / temperature
    if top_k > 0:
        v_local = logits.shape[-1]
        k_local = min(top_k, v_local)
        local_vals, local_idx = lax.top_k(logits, k_local)
        gidx = lax.axis_index("tp") * v_local + local_idx
        all_vals = lax.all_gather(
            local_vals, "tp", axis=-1, tiled=True
        )  # [B, tp*k]
        all_idx = lax.all_gather(gidx, "tp", axis=-1, tiled=True)
        # Oversized top_k degrades to full-vocab sampling (clamped on both
        # the local and the gathered pick).
        k_glob = min(top_k, all_vals.shape[-1])
        order = jnp.argsort(-all_vals, axis=-1, stable=True)[..., :k_glob]
        sel_vals = jnp.take_along_axis(all_vals, order, axis=-1)
        sel_idx = jnp.take_along_axis(all_idx, order, axis=-1)
        thresh = sel_vals[..., -1:]
        # Highest selected index among threshold-valued winners: tied
        # logits above it did not make the cut.
        idx_cut = jnp.max(
            jnp.where(sel_vals == thresh, sel_idx, -1), axis=-1, keepdims=True
        )
        my_gidx = lax.axis_index("tp") * v_local + jnp.arange(v_local)
        keep = (logits > thresh) | (
            (logits == thresh) & (my_gidx[None, :] <= idx_cut)
        )
        z = jnp.where(keep, z, NEG_INF)
    step_key = jax.random.fold_in(key, pos)
    # Decorrelate noise across BOTH sharded axes a batch row can live on:
    # tp shards hold different vocab slices of the same rows (distinct
    # slices need distinct noise), dp shards hold different rows (identical
    # noise would collapse sampled diversity to B/dp).
    shard_key = jax.random.fold_in(step_key, lax.axis_index("tp"))
    shard_key = jax.random.fold_in(shard_key, lax.axis_index("dp"))
    gumbel = jax.random.gumbel(shard_key, z.shape, jnp.float32)
    return _global_argmax(z + gumbel)


def build_generate(
    config: TransformerConfig,
    mesh: Mesh,
    max_new_tokens: int,
    temperature: float = 0.0,
    top_k: int = 0,
    quantized: bool = False,
    quantized_kv: bool = False,
):
    """Returns jitted generate(params, prompt [B, T_prompt], key=None) ->
    tokens [B, T_prompt + max_new_tokens].

    temperature == 0 (default) decodes greedily; temperature > 0 samples
    from softmax(logits/temperature) via sharded Gumbel-max (`_pick_token`),
    optionally truncated to the global top_k logits. `key` seeds sampling
    (defaults to jax.random.key(0)); it is ignored when greedy.

    Requires pp == sp == ep == 1 on the mesh (serving shape); dp and tp are
    free. The prompt is consumed in one batched causal prefill pass (filling
    the KV cache for all prompt positions with MXU-shaped matmuls and a
    single parameter stream), then new tokens decode through the cached
    step — still one compiled program."""
    cfg = config
    for axis in ("pp", "sp", "ep"):
        if axis_size(mesh, axis) != 1:
            raise ValueError(
                f"build_generate needs {axis}=1 (got {axis_size(mesh, axis)}); "
                "use a dp/tp serving mesh"
            )
    specs = param_specs(cfg)
    if quantized:
        # Params came through quant.quantize_params_for_serving: every
        # quantized weight is a (q, scale) pair whose sharding mirrors the
        # original weight (scales are unsharded on the contraction axis).
        from .quant import quantize_specs

        specs = quantize_specs(specs)
    cache_spec = P(None, "dp", None, "tp", None)

    def local_generate(params, prompt, key, cache_k, cache_v):
        t_prompt = prompt.shape[1]
        # Serving is HBM-bandwidth-bound: every decode step streams the full
        # parameter set. Cast float params to the compute dtype ONCE here
        # (outside the scan) so each step reads 2-byte weights instead of
        # re-reading the 4-byte training copies — roughly halving the
        # per-token traffic that sets the latency floor. The MoE router gate
        # `wg` is exempt: routing reads it in f32 for training-identical
        # expert selection, and pre-rounding it would flip near-tie routes.
        def _cast(path, x):
            if any(getattr(k, "key", None) == "wg" for k in path):
                return x
            # Quantization scales stay f32: they are tiny (one per output
            # channel) and bf16 rounding would add error on every weight.
            if any(getattr(k, "name", None) == "scale" for k in path):
                return x
            if jnp.issubdtype(x.dtype, jnp.floating):
                return x.astype(cfg.dtype)
            return x

        params = jax.tree_util.tree_map_with_path(_cast, params)
        # Scan carries must enter with the types the body produces. Tokens
        # end up varying over dp plus the params' size-1 pp axis — NOT tp,
        # which _global_argmax reduces away; promoting tokens to tp-varying
        # would make the final psum double them across the tp shards. The
        # cache picks up the params' full vma through the projections.
        params_vma = vma_union(params)
        token_vma = vma_union(prompt) | (params_vma - {"tp"})
        cache_vma = vma_union(cache_k) | params_vma
        cache = jax.tree.map(
            lambda a: pvary_to(a, cache_vma), {"k": cache_k, "v": cache_v}
        )

        # Phase 1 — prefill: one batched causal pass fills the cache for
        # every prompt position and yields the first generated token.
        last_logits, cache = _prefill_logits(params, prompt, cache, cfg)
        first = pvary_to(
            _pick_token(last_logits, key, t_prompt - 1, temperature, top_k),
            token_vma,
        )
        cache = jax.tree.map(lambda c: pvary_to(c, cache_vma), cache)

        # Phase 2 — decode: scan only the NEW positions, each feeding the
        # previous pick through the cached step. max_new_tokens is static,
        # so the zero case (prefill only, return the prompt unchanged —
        # the documented [B, T_prompt + max_new_tokens] contract) is a
        # trace-time branch.
        def step(carry, pos):
            token, cache = carry
            logits, cache = _token_logits(params, token, cache, pos, cfg)
            picked = pvary_to(
                _pick_token(logits, key, pos, temperature, top_k), token_vma
            )
            cache = jax.tree.map(lambda c: pvary_to(c, cache_vma), cache)
            return (picked, cache), picked

        parts = [pvary_to(prompt, token_vma)]
        if max_new_tokens > 0:
            (_, _), rest = lax.scan(
                step,
                (first, cache),
                t_prompt + jnp.arange(max_new_tokens - 1),
            )
            parts += [first[:, None], jnp.moveaxis(rest, 0, 1)]
        out = jnp.concatenate(parts, axis=1)
        # The output spec is P('dp', None): reduce away the helper axes the
        # params dragged in — all enforced size-1 (pp/sp/ep), where psum is
        # the identity.
        extra = tuple(
            getattr(jax.typeof(out), "vma", frozenset()) - {"dp"}
        )
        return lax.psum(out, extra) if extra else out

    sharded = jax.shard_map(
        local_generate,
        mesh=mesh,
        in_specs=(specs, P("dp", None), P(), cache_spec, cache_spec),
        out_specs=P("dp", None),
    )

    @jax.jit
    def generate(params, prompt, key=None):
        if key is None:
            key = jax.random.key(0)
        cache = init_kv_cache(
            cfg, mesh, prompt.shape[0], prompt.shape[1] + max_new_tokens,
            quantized_kv=quantized_kv,
        )
        return sharded(params, prompt, key, cache["k"], cache["v"])

    return generate
