"""Weight-only int8 quantization for the serving path.

Decode is HBM-bandwidth-bound: every step streams the full parameter set,
so serving latency is set by weight bytes, not FLOPs. The decode path
already pre-casts float weights to the bf16 compute dtype once per call
(`decode.build_generate`); int8 weights halve the traffic again. Scheme:

* per-output-channel symmetric int8 — for every matmul weight the
  contraction axis is the second-to-last (the layout shared by all of
  wq/wk/wv/wo/w1/w2/we1/we2/unembed), so scales are the abs-max over
  axis=-2 divided by 127, kept RANK-PRESERVED ([..., 1, d_out]) so the
  quantized pair reuses the weight's sharding layout;
* dequantization happens at the matmul sites (`weight_cast`), where XLA
  fuses the int8->bf16 convert + scale multiply into the dot's operand
  read — the weight crosses HBM as 1 byte/element and never
  materializes in bf16;
* norms, the MoE router gate (read in f32 for routing stability), and
  the embedding table (a gather, and the quality-sensitive tied-unembed
  case) stay in full precision.

The reference has no inference surface (it orchestrates containers); this
is a serving-plane extension like the rest of `models/decode.py`.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

# Weight names quantized for serving; all contract over axis -2.
QUANTIZED_WEIGHTS = frozenset(
    {"wq", "wk", "wv", "wo", "w1", "w2", "we1", "we2", "unembed"}
)


@jax.tree_util.register_pytree_with_keys_class
@dataclass
class QuantizedTensor:
    """int8 values + rank-preserved per-output-channel f32 scales.

    A pytree node, so quantized params flow through tree_map/shard_map
    like plain weights; `weight_cast` dequantizes at the matmul site.
    """

    q: Any  # int8, the original weight's shape
    scale: Any  # f32, shape with 1 at the contraction axis (-2)

    def tree_flatten_with_keys(self):
        return (
            ((jax.tree_util.GetAttrKey("q"), self.q),
             (jax.tree_util.GetAttrKey("scale"), self.scale)),
            None,
        )

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    @property
    def shape(self):
        return self.q.shape


def quantize_int8(w, axis: int = -2) -> QuantizedTensor:
    """Symmetric per-channel int8: one scale per slice along `axis`
    (weights reduce the contraction axis -2; the KV cache reduces the
    vector axis -1). The single definition of the serving quantization
    recipe — scale floor, rounding, clip range."""
    w = jnp.asarray(w)
    absmax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=axis, keepdims=True)
    scale = jnp.maximum(absmax, 1e-12) / 127.0
    q = jnp.clip(jnp.round(w.astype(jnp.float32) / scale), -127, 127).astype(
        jnp.int8
    )
    return QuantizedTensor(q=q, scale=scale.astype(jnp.float32))


def weight_cast(w, dtype):
    """Matmul-site weight fetch: plain cast for arrays, fused
    dequantization for QuantizedTensor (int8 bytes cross HBM; the
    convert+scale fuses into the dot)."""
    if isinstance(w, QuantizedTensor):
        # Dequantize in f32 and round ONCE to the compute dtype (casting
        # the scale to bf16 first would add ~0.4% rounding on every
        # weight); XLA fuses the whole convert+scale chain into the dot's
        # operand read, so only int8 bytes cross HBM either way.
        return (w.q.astype(jnp.float32) * w.scale).astype(dtype)
    return w.astype(dtype)


def quantize_params_for_serving(params: dict) -> dict:
    """Quantize every serving-relevant matmul weight in a transformer
    param tree (QUANTIZED_WEIGHTS by name); everything else passes
    through unchanged. Works on nested dicts (the layers sub-tree)."""

    def walk(d):
        out = {}
        for name, value in d.items():
            if isinstance(value, dict):
                out[name] = walk(value)
            elif name in QUANTIZED_WEIGHTS:
                out[name] = quantize_int8(value)
            else:
                out[name] = value
        return out

    return walk(params)


def quantize_specs(specs: dict) -> dict:
    """Mirror quantize_params_for_serving on a PartitionSpec tree: each
    quantized weight's spec becomes QuantizedTensor(q=orig, scale=orig
    with the contraction axis unsharded — the scale is size-1 there)."""

    def walk(d):
        out = {}
        for name, value in d.items():
            if isinstance(value, dict):
                out[name] = walk(value)
            elif name in QUANTIZED_WEIGHTS:
                entries = list(value)
                entries[-2] = None
                out[name] = QuantizedTensor(q=value, scale=P(*entries))
            else:
                out[name] = value
        return out

    return walk(specs)
