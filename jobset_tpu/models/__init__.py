"""Model zoo: flagship SPMD transformer (dense + MoE), ResNet-style CNN
(vision family), and the MLP smoke model."""

from . import cnn, decode, mlp, quant  # noqa: F401
from .cnn import CNNConfig  # noqa: F401
from .decode import build_generate  # noqa: F401
from .quant import quantize_params_for_serving  # noqa: F401
from .transformer import (
    TransformerConfig,
    build_forward,
    build_train_step,
    init_params,
    param_specs,
)

__all__ = [
    "CNNConfig",
    "build_generate",
    "TransformerConfig",
    "build_forward",
    "build_train_step",
    "cnn",
    "decode",
    "init_params",
    "mlp",
    "param_specs",
    "quant",
    "quantize_params_for_serving",
]
