"""Model zoo: flagship SPMD transformer (dense + MoE)."""

from .transformer import (
    TransformerConfig,
    build_forward,
    build_train_step,
    init_params,
    param_specs,
)

__all__ = [
    "TransformerConfig",
    "build_forward",
    "build_train_step",
    "init_params",
    "param_specs",
]
