"""Model zoo: flagship SPMD transformer (dense + MoE), ResNet-style CNN
(vision family), and the MLP smoke model."""

from . import cnn, mlp  # noqa: F401
from .cnn import CNNConfig  # noqa: F401
from .transformer import (
    TransformerConfig,
    build_forward,
    build_train_step,
    init_params,
    param_specs,
)

__all__ = [
    "CNNConfig",
    "TransformerConfig",
    "build_forward",
    "build_train_step",
    "cnn",
    "init_params",
    "mlp",
    "param_specs",
]
