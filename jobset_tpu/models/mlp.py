"""Small data-parallel MLP regression model.

The parity target for the reference's simple DDP examples
(`examples/pytorch/cnn-mnist`, SURVEY.md §2.2 DP row): batch sharded over
the dp axis, parameters replicated, gradients reduced by shard_map's VMA
transpose exactly as in the flagship transformer.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


@dataclass(frozen=True)
class MLPConfig:
    d_in: int = 32
    d_hidden: int = 128
    d_out: int = 1
    n_layers: int = 2


def init_params(rng: jax.Array, config: MLPConfig) -> dict:
    dims = [config.d_in] + [config.d_hidden] * (config.n_layers - 1) + [config.d_out]
    ks = jax.random.split(rng, len(dims) - 1)
    return {
        f"layer_{i}": {
            "w": jax.random.normal(ks[i], (dims[i], dims[i + 1])) / jnp.sqrt(dims[i]),
            "b": jnp.zeros((dims[i + 1],)),
        }
        for i in range(len(dims) - 1)
    }


def forward(params: dict, x: jax.Array) -> jax.Array:
    n = len(params)
    for i in range(n):
        layer = params[f"layer_{i}"]
        x = x @ layer["w"] + layer["b"]
        if i < n - 1:
            x = jax.nn.relu(x)
    return x


def build_train_step(config: MLPConfig, mesh: Mesh, optimizer):
    """MSE regression step, data-parallel over ('dp', 'sp') combined."""

    def local_step(params, x, y):
        def loss_fn(p):
            pred = forward(p, x)
            local = jnp.sum((pred - y) ** 2)
            count = jnp.asarray(x.shape[0], jnp.float32)
            return lax.psum(local, ("dp", "sp")) / lax.psum(count, ("dp", "sp"))

        return jax.value_and_grad(loss_fn)(params)

    sharded = jax.shard_map(
        local_step,
        mesh=mesh,
        in_specs=(P(), P(("dp", "sp")), P(("dp", "sp"))),
        out_specs=(P(), P()),
    )

    @partial(jax.jit, donate_argnums=(0, 1))
    def train_step(params, opt_state, batch):
        loss, grads = sharded(params, batch["x"], batch["y"])
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = jax.tree.map(lambda p, u: p + u, params, updates)
        # Replicate the scalar across the FULL mesh: without the constraint
        # XLA may place it on one device, leaving other processes of a
        # multi-host gang without an addressable shard to read.
        loss = jax.lax.with_sharding_constraint(
            loss, NamedSharding(mesh, P())
        )
        return params, opt_state, loss

    return train_step
