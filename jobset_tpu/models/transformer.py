"""Flagship decoder-only transformer, written mesh-first.

The whole model is one SPMD program under `shard_map` over the five-axis
mesh (`jobset_tpu.parallel.mesh`): every collective is explicit, in the
style of the scaling-book recipe — pick a mesh, place shards, let the
program say exactly which axis each reduction rides:

* **tp** — Megatron-style column/row parallel projections: QKV and MLP
  up-projections are column-sharded (no collective), output projections are
  row-sharded partial sums -> `psum('tp')`; vocab is sharded for both the
  one-hot embedding lookup and the log-softmax loss (psum-max / psum).
* **sp** — sequence chunks; attention is exact ring attention
  (`parallel.ring_attention`) with K/V blocks rotating via `ppermute`.
* **pp** — layer stages marched by the GPipe transform
  (`parallel.pipeline`); backward schedule comes from autodiff.
* **ep** — MoE expert shards. Four dispatch modes: dense (soft) dispatch
  (`moe_top_k=0`): every rank runs its local experts on all tokens,
  gate-weighted partials `psum('ep')`-ed; token-routed (`moe_top_k>0`):
  top-k capacity routing with `all_to_all` slot exchange over the ep axis
  (`_moe_mlp_routed`) — the sparse ICI-native path; dropless token-routed
  (`moe_dispatch="dropless"`): exact sorted ragged grouped matmuls,
  no capacity, no drops, any ep (`_moe_mlp_dropless`); expert-choice
  (`moe_router="expert"`): each expert takes its top-C tokens, perfectly
  balanced, no aux loss (`_moe_mlp_expert_choice`).
* **dp** — pure data parallelism; gradients are `psum`-ed over (dp, sp) and
  any other axis a parameter is replicated on.

Compute dtype defaults to bfloat16 (MXU-native) with float32 parameters and
f32 softmax/norm statistics; per-layer rematerialization (`jax.checkpoint`)
trades FLOPs for HBM.

Capability mapping to the reference: JobSet only orchestrates containers
that run frameworks like this (SURVEY.md §2.2); the model itself is
greenfield TPU-native work.
"""

from __future__ import annotations

from dataclasses import dataclass, replace as dc_replace
from functools import partial
from typing import Any

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.ad_checkpoint import checkpoint_name
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..parallel.mesh import MeshConfig, axis_size, pvary_to, vma_union
from ..parallel.pipeline import (
    pipeline_1f1b_grads,
    pipeline_apply,
    pipeline_apply_interleaved,
)
from ..ops.flash_block import _repeat_heads as repeat_kv  # GQA broadcast
from ..parallel.ring_attention import ring_attention
from .quant import weight_cast
from ..parallel.ulysses_attention import ulysses_attention


@dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32000
    d_model: int = 512
    n_heads: int = 8
    # Grouped-query attention: number of K/V heads (0 = n_heads, i.e. MHA).
    # Each group of n_heads/n_kv_heads query heads shares one K/V head —
    # the KV cache (the serving working set) and the wk/wv parameters
    # shrink by the same factor; Q/attention math is unchanged (K/V are
    # broadcast per group at compute time).
    n_kv_heads: int = 0
    d_ff: int = 2048
    n_layers: int = 8
    # MoE: 0 experts = dense MLP in every layer.
    n_experts: int = 0
    d_ff_expert: int = 512
    # 0 = dense soft dispatch (every expert sees every token, gate-weighted
    # psum); k > 0 = token-choice top-k routing with a capacity buffer and
    # all_to_all dispatch over the ep axis (the ICI-native sparse path).
    moe_top_k: int = 0
    moe_capacity_factor: float = 1.25
    # Token-choice dispatch formulation:
    #   "capacity" — static per-expert capacity + all_to_all over ep
    #                (switch-style; overflow drops; the distributed path);
    #   "dropless" — exact sorted ragged grouped matmuls (MegaBlocks
    #                -style, lax.ragged_dot): no capacity, no drops, paying
    #                only activated FLOPs. Works at any ep: each ep shard
    #                runs the ragged path over its locally-owned experts
    #                (locality-keyed sort, no dispatch collective) and one
    #                psum combines — see _moe_mlp_dropless.
    moe_dispatch: str = "capacity"
    # Router family for n_experts > 0: "token" = token-choice (dense soft
    # dispatch at moe_top_k=0, switch-style top-k routing otherwise);
    # "expert" = expert-choice (each expert takes its top-C tokens,
    # perfectly balanced, no aux loss, moe_top_k ignored).
    moe_router: str = "token"
    # Load-balancing auxiliary loss weight (GShard/Switch style), applied
    # only on the routed path — without it token-choice routing collapses
    # onto a few experts.
    moe_aux_coef: float = 0.01
    max_seq_len: int = 2048
    rope_theta: float = 10000.0
    norm_eps: float = 1e-6
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    remat: bool = True
    # Rematerialization policy when remat is on:
    #   "full" — save only layer boundaries, recompute everything (minimum
    #            memory, ~1/3 extra forward FLOPs on the backward);
    #   "dots" — save matmul/einsum outputs plus the named flash-attention
    #            output (see _stage_fn), recompute elementwise-only work
    #            (norms, rotary, activations). Costs a few saved
    #            activations per layer but keeps the backward's recompute
    #            off the MXU — the usual MFU-friendly operating point.
    remat_policy: str = "full"
    n_microbatches: int = 0  # 0 -> defaults to pp size
    # Pipeline schedule over the pp axis:
    #   "gpipe"       — one contiguous stage per rank; bubble
    #                   (pp-1)/(n_micro+pp-1); activation memory grows
    #                   with n_micro (one autodiff'd scan).
    #   "interleaved" — pipeline_virtual chunks per rank (Megatron
    #                   virtual stages); a microbatch wraps the ring
    #                   pipeline_virtual times and the bubble shrinks
    #                   ~pipeline_virtual-fold (parallel.pipeline
    #                   docstring has the timetable). Same logical model:
    #                   a GPipe layout converts exactly via
    #                   `interleave_stage_params`. Activation memory
    #                   still grows with n_micro.
    #   "1f1b"        — memory-capped 1F1B: per-microbatch VJPs driven by
    #                   a host-built timetable bound in-flight activations
    #                   to O(pp) microbatches regardless of n_micro
    #                   (pipeline_1f1b_grads). Training-path only (eval /
    #                   plain forward fall back to the gpipe wavefront).
    #                   Dense, soft-dispatch and expert-choice MoE all
    #                   work; token-choice top-k routing is excluded (its
    #                   balancing aux is normalized over the GLOBAL batch,
    #                   which a schedule that starts backwards before all
    #                   forwards finish cannot see).
    pipeline_schedule: str = "gpipe"
    pipeline_virtual: int = 1  # chunks per rank (interleaved only)
    # Chunk the loss over the time axis (0 = off): the unembed projection
    # and cross-entropy run per chunk under jax.checkpoint inside a scan,
    # so the [B, T, vocab] logits tensor — often the peak-memory term at
    # large batch — never materializes; only [B, loss_chunk, vocab] does.
    # Numerically exact (the loss is a per-token sum); T_local must divide
    # by the chunk. The knob is an UPPER BOUND on resident logits: when it
    # is >= the local sequence length the unchunked path already satisfies
    # it, so chunking (and its backward recompute) is skipped.
    loss_chunk: int = 0
    # Stability knobs (both 0 = off): label smoothing mixes eps/V uniform
    # mass into the target distribution; z-loss adds coef*log^2(Z) to keep
    # the softmax partition function near 1 (ST-MoE/PaLM recipe).
    label_smoothing: float = 0.0
    z_loss_coef: float = 0.0
    # Tie the output projection to the embedding (logits = x @ embed^T):
    # halves the vocab parameter count; both uses share one vocab-sharded
    # [V, d] matrix and gradients flow into it from both ends.
    tie_embeddings: bool = False
    # Sequence-parallel attention strategy over the sp axis: "ring" rotates
    # K/V around the torus (head-count-independent sp, O(T_local) K/V
    # resident); "ulysses" re-shards heads with two all_to_alls (cheaper
    # collectives for moderate sp, needs n_heads/(tp*sp) >= 1 integral).
    # Both are exact; see parallel/ulysses_attention.py for the trade-off.
    attn_impl: str = "ring"

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    @property
    def kv_heads(self) -> int:
        return self.n_kv_heads or self.n_heads

    def validate(self, mesh_config: MeshConfig) -> None:
        mc = mesh_config
        if self.d_model % self.n_heads:
            raise ValueError("d_model must divide evenly into heads")
        if self.n_layers % mc.pp:
            raise ValueError(f"n_layers {self.n_layers} not divisible by pp {mc.pp}")
        if self.n_heads % mc.tp:
            raise ValueError(f"n_heads {self.n_heads} not divisible by tp {mc.tp}")
        if self.n_heads % self.kv_heads:
            raise ValueError(
                f"n_heads {self.n_heads} not divisible by "
                f"n_kv_heads {self.kv_heads}"
            )
        if self.kv_heads % mc.tp:
            raise ValueError(
                f"n_kv_heads {self.kv_heads} not divisible by tp {mc.tp}"
            )
        if self.d_ff % mc.tp or (self.n_experts and self.d_ff_expert % mc.tp):
            raise ValueError("feed-forward widths must be divisible by tp")
        if self.vocab_size % mc.tp:
            raise ValueError(f"vocab {self.vocab_size} not divisible by tp {mc.tp}")
        if self.n_experts % max(mc.ep, 1):
            raise ValueError("n_experts must be divisible by ep")
        if self.moe_router not in ("token", "expert"):
            raise ValueError(f"unknown moe_router {self.moe_router!r}")
        if self.moe_router == "expert" and not self.n_experts:
            raise ValueError("moe_router='expert' requires n_experts > 0")
        if self.moe_top_k and not self.n_experts:
            raise ValueError("moe_top_k requires n_experts > 0")
        if self.moe_dispatch not in ("capacity", "dropless"):
            raise ValueError(
                f"unknown moe_dispatch {self.moe_dispatch!r} "
                "(expected 'capacity' or 'dropless')"
            )
        if self.moe_dispatch == "dropless" and (
            self.moe_top_k == 0 or self.moe_router == "expert"
        ):
            raise ValueError(
                "moe_dispatch='dropless' applies to token-choice top-k "
                "routing only (set moe_top_k > 0 and moe_router='token'); "
                "it would be silently ignored here"
            )
        if self.moe_top_k > self.n_experts > 0:
            raise ValueError(
                f"moe_top_k {self.moe_top_k} exceeds n_experts {self.n_experts}"
            )
        if self.loss_chunk < 0:
            raise ValueError(f"loss_chunk must be >= 0, got {self.loss_chunk}")
        if not 0.0 <= self.label_smoothing < 1.0:
            raise ValueError(
                f"label_smoothing must be in [0, 1), got {self.label_smoothing}"
            )
        if self.z_loss_coef < 0.0:
            raise ValueError(f"z_loss_coef must be >= 0, got {self.z_loss_coef}")
        if self.attn_impl not in ("ring", "ulysses"):
            raise ValueError(f"unknown attn_impl {self.attn_impl!r}")
        if self.remat_policy not in ("full", "dots"):
            raise ValueError(
                f"unknown remat_policy {self.remat_policy!r} "
                "(expected 'full' or 'dots')"
            )
        if self.pipeline_schedule not in ("gpipe", "interleaved", "1f1b"):
            raise ValueError(
                f"unknown pipeline_schedule {self.pipeline_schedule!r} "
                "(expected 'gpipe', 'interleaved' or '1f1b')"
            )
        if self.pipeline_virtual < 1:
            raise ValueError("pipeline_virtual must be >= 1")
        if self.pipeline_schedule != "interleaved" and self.pipeline_virtual != 1:
            raise ValueError("pipeline_virtual > 1 requires 'interleaved'")
        if (
            self.pipeline_schedule == "1f1b"
            and self.moe_top_k > 0
            and self.moe_router == "token"
        ):
            raise ValueError(
                "pipeline_schedule='1f1b' does not support token-choice "
                "top-k routing (moe_top_k > 0): its balancing aux is "
                "normalized over the global batch, which a schedule that "
                "starts backwards before all forwards finish cannot see. "
                "Dense, soft-dispatch and expert-choice MoE models work "
                "(none carries a batch-global aux)."
            )
        if self.pipeline_schedule == "interleaved":
            lps = self.n_layers // max(mc.pp, 1)
            if lps % self.pipeline_virtual:
                raise ValueError(
                    f"layers per stage ({lps}) not divisible by "
                    f"pipeline_virtual ({self.pipeline_virtual})"
                )
        if self.attn_impl == "ulysses" and (self.n_heads // mc.tp) % mc.sp:
            raise ValueError(
                f"ulysses attention requires heads-per-tp-rank "
                f"({self.n_heads // mc.tp}) divisible by sp ({mc.sp})"
            )


# ---------------------------------------------------------------------------
# Parameters
# ---------------------------------------------------------------------------


def param_specs(config: TransformerConfig) -> dict:
    """PartitionSpec pytree. Layer leaves are stacked [pp, layers_per_stage,
    ...]; tensor dims shard over tp, expert dims over ep."""
    specs = {
        "embed": P("tp", None),  # vocab-sharded
        "final_norm": P(None),
        "layers": {
            "ln1": P("pp", None, None),
            "ln2": P("pp", None, None),
            "wq": P("pp", None, None, "tp"),
            "wk": P("pp", None, None, "tp"),
            "wv": P("pp", None, None, "tp"),
            "wo": P("pp", None, "tp", None),
        },
    }
    if not config.tie_embeddings:
        specs["unembed"] = P(None, "tp")
    if config.n_experts:
        specs["layers"].update(
            {
                "wg": P("pp", None, None, None),
                "we1": P("pp", None, "ep", None, "tp"),
                "we2": P("pp", None, "ep", "tp", None),
            }
        )
    else:
        specs["layers"].update(
            {
                "w1": P("pp", None, None, "tp"),
                "w2": P("pp", None, "tp", None),
            }
        )
    return specs


def init_params(
    rng: jax.Array, config: TransformerConfig, mesh: Mesh
) -> dict:
    """Initialize global parameter arrays, placed with their NamedShardings."""
    cfg = config
    pp = axis_size(mesh, "pp")
    lps = cfg.n_layers // pp
    d, h, dh = cfg.d_model, cfg.n_heads, cfg.head_dim

    def dense_init(key, shape, fan_in):
        return (jax.random.normal(key, shape, cfg.param_dtype) / np.sqrt(fan_in))

    k = jax.random.split(rng, 16)
    layer_shapes = {
        "ln1": ((pp, lps, d), None),
        "ln2": ((pp, lps, d), None),
        "wq": ((pp, lps, d, h * dh), d),
        "wk": ((pp, lps, d, cfg.kv_heads * dh), d),
        "wv": ((pp, lps, d, cfg.kv_heads * dh), d),
        "wo": ((pp, lps, h * dh, d), h * dh),
    }
    if cfg.n_experts:
        layer_shapes.update(
            {
                "wg": ((pp, lps, d, cfg.n_experts), d),
                "we1": ((pp, lps, cfg.n_experts, d, cfg.d_ff_expert), d),
                "we2": ((pp, lps, cfg.n_experts, cfg.d_ff_expert, d), cfg.d_ff_expert),
            }
        )
    else:
        layer_shapes.update(
            {
                "w1": ((pp, lps, d, cfg.d_ff), d),
                "w2": ((pp, lps, cfg.d_ff, d), cfg.d_ff),
            }
        )

    params = {
        "embed": dense_init(k[0], (cfg.vocab_size, d), d),
        "final_norm": jnp.ones((d,), cfg.param_dtype),
        "layers": {},
    }
    if not cfg.tie_embeddings:
        params["unembed"] = dense_init(k[1], (d, cfg.vocab_size), d)
    for i, (name, (shape, fan_in)) in enumerate(layer_shapes.items()):
        if fan_in is None:
            params["layers"][name] = jnp.ones(shape, cfg.param_dtype)
        else:
            params["layers"][name] = dense_init(k[2 + i], shape, fan_in)

    specs = param_specs(cfg)
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), params, specs
    )


# ---------------------------------------------------------------------------
# Forward pieces (all run inside shard_map on local shards)
# ---------------------------------------------------------------------------


def rms_norm(x, scale, eps):
    x32 = x.astype(jnp.float32)
    normed = x32 * lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + eps)
    return (normed * scale.astype(jnp.float32)).astype(x.dtype)


def rotary(x, positions, theta):
    """x: [..., T, H, D]; positions: [T]."""
    dim = x.shape[-1]
    half = dim // 2
    freqs = positions[:, None] / (
        theta ** (jnp.arange(half, dtype=jnp.float32) / half)
    )  # [T, half]
    cos = jnp.cos(freqs)[None, :, None, :].astype(x.dtype)
    sin = jnp.sin(freqs)[None, :, None, :].astype(x.dtype)
    x1, x2 = x[..., :half], x[..., half:]
    return jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)


def _attention_block(p, x, cfg: TransformerConfig, t_local: int):
    """Megatron column/row parallel attention (ring or Ulysses over sp)."""
    tp = lax.psum(1, "tp")
    heads_local = cfg.n_heads // tp
    kv_heads_local = cfg.kv_heads // tp
    positions = (
        lax.axis_index("sp") * t_local + jnp.arange(t_local, dtype=jnp.float32)
    )

    xn = rms_norm(x, p["ln1"], cfg.norm_eps)
    compute = cfg.dtype

    # Fused QKV: one [d, (h + 2*hkv)*dh] GEMM instead of three narrow
    # ones — same dot products column-for-column (bitwise identical),
    # but the MXU sees one wide matmul, which matters exactly where the
    # roofline says the flagship loses MFU (narrow d_model operands).
    # XLA folds the weight concat into the GEMM's operand read.
    q_width = heads_local * cfg.head_dim
    kv_width = kv_heads_local * cfg.head_dim
    w_qkv = jnp.concatenate([
        weight_cast(p["wq"], compute),
        weight_cast(p["wk"], compute),
        weight_cast(p["wv"], compute),
    ], axis=1)
    qkv = jnp.einsum("btd,df->btf", xn.astype(compute), w_qkv)
    q, key, value = jnp.split(qkv, [q_width, q_width + kv_width], axis=-1)

    def heads(y, n_heads):
        return y.reshape(*y.shape[:-1], n_heads, cfg.head_dim)

    group = heads_local // kv_heads_local
    q = rotary(heads(q, heads_local), positions, cfg.rope_theta)
    key = rotary(heads(key, kv_heads_local), positions, cfg.rope_theta)
    value = heads(value, kv_heads_local)
    if cfg.attn_impl == "ulysses":
        # Ulysses splits the head axis across sp. When sp divides the
        # compact kv head count, each rank's post-split q heads map exactly onto
        # its kv heads (both splits are head-major), so compact K/V ride
        # the all_to_alls and the blockwise fold broadcasts per block —
        # the same group-times ICI saving the ring path gets. Only the
        # indivisible corner case must pre-broadcast to keep q/kv groups
        # rank-aligned.
        sp = lax.psum(1, "sp")
        if kv_heads_local % sp:
            key, value = repeat_kv(key, group), repeat_kv(value, group)
        attn = ulysses_attention(q, key, value, "sp", causal=True)
    else:
        # Ring has no alignment constraint: compact K/V ride the ppermutes.
        attn = ring_attention(q, key, value, "sp", causal=True)
    # Named checkpoint for remat_policy='dots': the attention result comes
    # from the custom-VJP flash kernel, NOT a dot primitive, so the
    # checkpoint_dots policy alone would re-run the whole attention fold
    # (ring collectives included) on the backward. Tagging it lets the
    # policy save it like the other matmul outputs.
    attn = checkpoint_name(attn, "flash_attn_out")
    attn = attn.reshape(*attn.shape[:-2], heads_local * cfg.head_dim)
    out = jnp.einsum("btf,fd->btd", attn.astype(compute),
                     weight_cast(p["wo"], compute))
    out = lax.psum(out, "tp")
    return x + out.astype(x.dtype)


def _dense_mlp(p, xn, cfg):
    compute = cfg.dtype
    h = jax.nn.silu(
        jnp.einsum("btd,df->btf", xn.astype(compute), weight_cast(p["w1"], compute))
    )
    out = jnp.einsum("btf,fd->btd", h, weight_cast(p["w2"], compute))
    return lax.psum(out, "tp")


def _moe_mlp(p, xn, cfg):
    """Dense-dispatch MoE: local experts on all local tokens, gate-weighted
    partial outputs psum'd over ('ep', 'tp')."""
    compute = cfg.dtype
    ep = lax.psum(1, "ep")
    e_local = cfg.n_experts // ep
    gates = jax.nn.softmax(
        jnp.einsum(
            "btd,de->bte", xn.astype(jnp.float32), p["wg"].astype(jnp.float32)
        ),
        axis=-1,
    )  # [B, T, E_global], f32 for routing stability
    start = lax.axis_index("ep") * e_local
    gates_local = lax.dynamic_slice_in_dim(gates, start, e_local, axis=2)

    h = jax.nn.silu(
        jnp.einsum("btd,edf->ebtf", xn.astype(compute),
                   weight_cast(p["we1"], compute))
    )
    y = jnp.einsum("ebtf,efd->ebtd", h, weight_cast(p["we2"], compute))
    out = jnp.einsum("ebtd,bte->btd", y, gates_local.astype(compute))
    return lax.psum(out, ("ep", "tp"))


def _moe_mlp_routed(p, xn, cfg):
    """Token-choice top-k routing with all_to_all expert dispatch — the
    ICI-native sparse path (SURVEY.md §2.2 EP row: "all-to-all over ICI").

    Tokens enter replicated across `ep` (the batch shards over dp/sp), so
    the block first splits the token set: each ep rank routes its own
    1/ep chunk to top-k experts under a static per-expert capacity C
    (overflow drops, standard switch-style), packs an expert-major
    [E, C, d] buffer, and one `all_to_all` over `ep` ships every slot to
    the rank owning its expert — genuinely distinct data in every lane.
    After the expert FFN (weights column/row split over tp, one psum) a
    reverse all_to_all returns the slots and a tiled `all_gather` over `ep`
    concatenates the rank-ordered disjoint chunks back into the full token
    set. The gathered output is numerically identical on every ep rank but
    stays *typed* ep-varying in shard_map's vma system (all_gather, unlike
    psum, does not erase the axis); the loss reduction normalizes that by
    psumming over ep and dividing the group product back out. Routing
    compute and expert FLOPs are both 1/ep of the soft dispatch's, scaled
    by k * capacity_factor / n_experts.
    """
    num_experts, k = cfg.n_experts, cfg.moe_top_k
    b, t, d = xn.shape
    chunk, gates, n_chunk = _route_prologue(p, xn, cfg)
    top_w, top_i = renormalized_topk(gates, k)  # [n_chunk, k]

    # Per-layer balancing statistics for the GShard aux loss (E*sum f_e*P_e):
    # raw per-expert choice counts and gate-probability sums over this
    # rank's chunk. The aux itself is formed in `_local_loss_fn` from the
    # globally-psummed, microbatch-pooled stats: E*sum(f*P) is nonlinear in
    # the token chunking, so per-chunk aux values averaged after the fact
    # would make the training objective depend on the mesh shape and the
    # microbatch count; pooling the linear stats first makes the objective
    # the global-batch computation on any mesh, and costs ONE fused psum
    # per step instead of a latency-bound collective inside every layer.
    choice_onehot = jax.nn.one_hot(top_i, num_experts, dtype=jnp.float32)
    stats = jnp.stack(
        [jnp.sum(choice_onehot, axis=(0, 1)), jnp.sum(gates, axis=0)]
    )  # [2, E]: choice counts, gate-prob sums

    # Static capacity: each expert accepts at most C slots per source rank.
    capacity = max(
        1, int(np.ceil(k * n_chunk / num_experts * cfg.moe_capacity_factor))
    )

    # Position of each (slot, token) choice inside its expert's buffer,
    # slot-major so first choices win capacity over second choices.
    flat = choice_onehot.transpose(1, 0, 2).reshape(k * n_chunk, num_experts)
    pos = jnp.cumsum(flat, axis=0) - flat  # [k*n, E]
    kept = flat * (pos < capacity)
    slot = jax.nn.one_hot(
        pos.astype(jnp.int32), capacity, dtype=jnp.float32
    )  # [k*n, E, C]
    dispatch = (kept[..., None] * slot).reshape(
        k, n_chunk, num_experts, capacity
    )
    weights = top_w.transpose(1, 0)[..., None, None]  # [k, n, 1, 1]
    combine = jnp.sum(dispatch * weights, axis=0)  # [n_chunk, E, C]
    dispatch = jnp.sum(dispatch, axis=0)  # [n_chunk, E, C]

    return (
        _dispatch_combine_experts(p, chunk, dispatch, combine, cfg).reshape(
            b, t, d
        ),
        stats,
    )


def _moe_mlp_dropless(p, xn, cfg):
    """Dropless token-choice top-k routing (MegaBlocks-style), any ep.

    Exact routed math with NO capacity buffers and NO token drops: each
    token's k (token, expert) slots are sorted by expert and the expert
    FFNs run as two grouped matmuls over the contiguous per-expert
    segments (`lax.ragged_dot` — the TPU grouped-GEMM primitive), paying
    only activated FLOPs. Differentiable end-to-end (sort/gather/ragged
    matmuls/scatter-add all carry VJPs); the balancing-aux statistics are
    the same [2, E] (choice counts, gate-prob sums) contract as the
    capacity path, so the loss-side pooling is identical.

    Expert parallelism (ep > 1) exploits the fact that the token set is
    ALREADY replicated over ep (the batch shards over dp/sp): instead of
    shipping ragged segments — which have no static all_to_all shape —
    every rank routes the full local token set, runs the grouped matmuls
    for just the slots of its own e_local expert shard (locality-keyed
    sort; see `sorted_ragged_expert_ffn`), and ONE `psum` over ('ep','tp')
    sums the disjoint partial outputs. No dispatch collective, no
    capacity, no padding: per-rank expert FLOPs stay exactly the
    activated count, weights stay sharded, and the only comm is the psum
    the dense-dispatch path already pays. Router compute (gates, top-k,
    the O(nk log nk) sort) is replicated over ep rather than 1/ep — the
    router is a [d, E] matmul plus VPU work, negligible next to the
    expert FFNs this path exists to scale. Exactness vs the ep=1 path
    and vs the capacity path at no-drop capacity is differential-tested
    (tests/test_transformer.py).

    Serving note: this is the training-side twin of the serving prefill's
    `decode._moe_mlp_topk_sorted`; a model trained dropless decodes
    exactly (all serving top-k formulations are exact).
    """
    k = cfg.moe_top_k
    compute = cfg.dtype
    b, t, d = xn.shape
    ep = lax.psum(1, "ep")
    ep_idx = lax.axis_index("ep")
    e_local = p["we1"].shape[0]  # this rank's expert shard
    x = xn.reshape(b * t, d)  # FULL local token set — no ep chunk split
    gates = jax.nn.softmax(
        jnp.einsum(
            "nd,de->ne", x.astype(jnp.float32), p["wg"].astype(jnp.float32)
        ),
        axis=-1,
    )  # [n, E] f32 routing
    top_w, top_i = renormalized_topk(gates, k)  # [n, k]

    out, _ = sorted_ragged_expert_ffn(
        p, x, top_w, top_i, cfg, local_experts=(ep_idx, e_local)
    )
    # Stats are computed over the full token set and thus replicated over
    # ep; the loss pools with a psum over ('dp','sp','ep'), so divide by
    # ep to keep the pooled global stats identical to the capacity path's
    # (which sums disjoint per-rank chunks).
    counts = jnp.bincount(
        top_i.reshape(-1), length=cfg.n_experts
    ).astype(jnp.float32)
    stats = jnp.stack([counts, jnp.sum(gates, axis=0)]) / ep
    out = lax.psum(out.astype(compute), ("ep", "tp"))
    return out.reshape(b, t, d), stats


def renormalized_topk(gates, k: int):
    """Top-k gate pick + sum-renormalization — THE routing weight
    definition, shared by every token-choice formulation (capacity,
    dropless, and the serving paths) so their per-token weights cannot
    drift. gates [..., E] f32; returns (top_w, top_i), each [..., k]."""
    top_w, top_i = lax.top_k(gates, k)
    return top_w / jnp.maximum(jnp.sum(top_w, -1, keepdims=True), 1e-9), top_i


def sorted_ragged_expert_ffn(p, x_flat, top_w, top_i, cfg, local_experts=None):
    """THE sorted ragged grouped-matmul core, shared by dropless training
    (`_moe_mlp_dropless`) and serving prefill (`decode._moe_mlp_topk_sorted`)
    so the exact train/serve parity both paths promise cannot drift.

    x_flat [n, d] tokens, top_w/top_i [n, k] renormalized gate picks.
    Replicates each token's k (token, expert) slots, sorts them by expert,
    runs the expert FFNs as two grouped matmuls over the contiguous
    per-expert segments (`lax.ragged_dot`), and combines gate-weighted
    results with an f32 scatter-add (k contributions per token accumulate
    without per-add bf16 rounding). Returns (out [n, d] f32 — caller
    psums over tp — and group_sizes int32, the per-expert choice counts).

    local_experts=(ep_idx, e_local): the expert-parallel form. p["we1/2"]
    hold only this rank's e_local-expert shard, so the sort key places
    slots routed to LOCAL experts first (grouped by local expert id) and
    every foreign slot under a trailing sentinel group that no weight
    group covers; foreign slots' gate weights are zeroed so the
    scatter-add accumulates exactly the local experts' contributions (the
    caller psums partial outputs over ep). group_sizes is then [e_local]
    and the grouped matmuls pay only for this rank's activated slots —
    the segments stay ragged end-to-end; nothing is shipped, because the
    token set is already replicated over ep (see `_moe_mlp_dropless`)."""
    num_experts, k = cfg.n_experts, cfg.moe_top_k
    compute = cfg.dtype
    n, d = x_flat.shape

    expert_of = top_i.reshape(n * k)  # slot order: token-major
    tok_of = jnp.repeat(jnp.arange(n), k)
    if local_experts is None:
        key, n_groups, keep = expert_of, num_experts, None
    else:
        ep_idx, e_local = local_experts
        n_groups = e_local
        keep = (expert_of // e_local) == ep_idx  # slot's expert is mine
        key = jnp.where(keep, expert_of - ep_idx * e_local, e_local)
    order = jnp.argsort(key)  # contiguous per-(local-)expert segments
    sorted_tok = tok_of[order]
    group_sizes = jnp.bincount(key, length=n_groups + 1)[:n_groups].astype(
        jnp.int32
    )

    xs = x_flat[sorted_tok].astype(compute)  # [n*k, d]
    h = jax.nn.silu(
        lax.ragged_dot(
            xs, weight_cast(p["we1"], compute), group_sizes,
            preferred_element_type=compute,
        )
    )
    y = lax.ragged_dot(
        h, weight_cast(p["we2"], compute), group_sizes,
        preferred_element_type=compute,
    )
    w_sorted = top_w.reshape(n * k)[order]
    if keep is not None:
        # Rows past sum(group_sizes) belong to no weight group; zeroing
        # their combine weight makes the partial output independent of
        # whatever ragged_dot leaves in uncovered rows.
        w_sorted = jnp.where(keep[order], w_sorted, 0.0)
    out = (
        jnp.zeros((n, d), jnp.float32)
        .at[sorted_tok]
        .add(y.astype(jnp.float32) * w_sorted[:, None])
    )
    return out, group_sizes


def _route_prologue(p, xn, cfg):
    """Shared router head: split the replicated token set into this ep
    rank's chunk and compute its f32 gate distribution. Returns
    (chunk [n_chunk, d], gates [n_chunk, E], n_chunk)."""
    ep = lax.psum(1, "ep")
    ep_idx = lax.axis_index("ep")
    b, t, d = xn.shape
    n_tok = b * t
    if n_tok % ep:
        raise ValueError(
            f"routed MoE needs local tokens ({n_tok}) divisible by ep ({ep})"
        )
    n_chunk = n_tok // ep
    x = xn.reshape(n_tok, d)
    chunk = lax.dynamic_slice_in_dim(x, ep_idx * n_chunk, n_chunk, axis=0)
    gates = jax.nn.softmax(
        jnp.einsum(
            "nd,de->ne", chunk.astype(jnp.float32), p["wg"].astype(jnp.float32)
        ),
        axis=-1,
    )  # [n_chunk, E] f32 routing
    return chunk, gates, n_chunk


def _dispatch_combine_experts(p, chunk, dispatch, combine, cfg):
    """The all_to_all expert dispatch shared by both routers: pack this ep
    rank's token chunk into expert-major [E, C, d] slot buffers per the
    boolean `dispatch` [n, E, C], ship every slot to the rank owning its
    expert, run the (tp column/row split) expert FFN, ship results back,
    and weight them into token positions per `combine` [n, E, C]. Returns
    the reassembled full local token set [n * ep, d] (all_gather over ep —
    chunks are disjoint in rank order, so it is a concatenation)."""
    compute = cfg.dtype
    ep = lax.psum(1, "ep")
    e_local = cfg.n_experts // ep
    num_experts = cfg.n_experts
    capacity = dispatch.shape[-1]
    d = chunk.shape[-1]

    send = jnp.einsum(
        "nd,nec->ecd", chunk.astype(compute), dispatch.astype(compute)
    ).reshape(ep, e_local, capacity, d)
    recv = lax.all_to_all(send, "ep", split_axis=0, concat_axis=0)
    # recv[s, e, c, :] = slot c for my expert e from source rank s.
    tokens_in = recv.transpose(1, 0, 2, 3).reshape(e_local, ep * capacity, d)

    h = jax.nn.silu(
        jnp.einsum("etd,edf->etf", tokens_in, p["we1"].astype(compute))
    )
    y = jnp.einsum("etf,efd->etd", h, p["we2"].astype(compute))
    y = lax.psum(y, "tp")  # row-parallel reduction, weights split over tp

    back = y.reshape(e_local, ep, capacity, d).transpose(1, 0, 2, 3)
    ret = lax.all_to_all(back, "ep", split_axis=0, concat_axis=0)
    ret = ret.reshape(num_experts, capacity, d)
    out_chunk = jnp.einsum(
        "ecd,nec->nd", ret.astype(compute), combine.astype(compute)
    )
    return lax.all_gather(out_chunk, "ep", tiled=True)


def _moe_mlp_expert_choice(p, xn, cfg):
    """Expert-choice routing (Zhou et al. 2022): each expert picks its
    top-C tokens by gate score — the transpose of token-choice. Perfectly
    load-balanced BY CONSTRUCTION (every expert processes exactly C
    slots), so no balancing aux loss is needed; the trade is that a token
    may be chosen by zero experts (its MLP output is then 0, the residual
    carries it) or by many.

    Same chunk-split + all_to_all dispatch fabric as the token-choice
    router. Choices are made over this ep rank's local token chunk (the
    standard practice — a per-device decision); consequently routing is
    NOT invariant to the dp/sp/ep chunking except in the full-capacity
    limit C >= n_chunk, where every expert takes every token and the
    output equals the dense soft dispatch exactly (differential-tested).
    """
    num_experts = cfg.n_experts
    b, t, d = xn.shape
    chunk, gates, n_chunk = _route_prologue(p, xn, cfg)

    capacity = min(
        n_chunk,
        max(1, int(np.ceil(n_chunk / num_experts * cfg.moe_capacity_factor))),
    )
    # Each expert's top-C tokens: scores transposed to expert-major.
    top_w, top_i = lax.top_k(gates.T, capacity)  # [E, C]
    sel = jax.nn.one_hot(top_i, n_chunk, dtype=jnp.float32)  # [E, C, n]
    dispatch = sel.transpose(2, 0, 1)  # [n, E, C]
    combine = dispatch * top_w[None, :, :]  # gate weight at the chosen slot

    out = _dispatch_combine_experts(p, chunk, dispatch, combine, cfg)
    stats = jnp.zeros((2, aux_stat_width(cfg)), jnp.float32)
    return out.reshape(b, t, d), stats


def aux_stat_width(cfg: TransformerConfig) -> int:
    """Trailing dimension of the per-layer aux statistics carried through
    the pipeline: per-expert choice counts + gate-prob sums on the routed
    path, a zero placeholder elsewhere (shapes must be config-static)."""
    return max(cfg.n_experts, 1)


def _layer(p, x, cfg: TransformerConfig, t_local: int):
    """Returns (x, stats): stats [2, E] are the routed-MoE balancing
    statistics (zeros on the dense and soft-dispatch paths)."""
    x = _attention_block(p, x, cfg, t_local)
    xn = rms_norm(x, p["ln2"], cfg.norm_eps)
    stats = jnp.zeros((2, aux_stat_width(cfg)), jnp.float32)
    if "wg" in p and cfg.moe_router == "expert":
        out, stats = _moe_mlp_expert_choice(p, xn, cfg)
    elif "wg" in p and cfg.moe_top_k > 0:
        if cfg.moe_dispatch == "dropless":
            out, stats = _moe_mlp_dropless(p, xn, cfg)
        else:
            out, stats = _moe_mlp_routed(p, xn, cfg)
    elif "wg" in p:
        out = _moe_mlp(p, xn, cfg)
    else:
        out = _dense_mlp(p, xn, cfg)
    return x + out.astype(x.dtype), stats


def _stage_fn(stage_params, x, cfg: TransformerConfig):
    """One pipeline stage: scan over this stage's layers. Returns
    (x, stats) — stats [layers_per_stage, 2, E] stacked per layer (the
    balancing aux is nonlinear in them, so layers stay separate until the
    loss function forms the per-layer products from global sums)."""
    t_local = x.shape[-2]

    def body(x, layer_p):
        fn = partial(_layer, cfg=cfg, t_local=t_local)
        if cfg.remat:
            if cfg.remat_policy == "dots":
                # Matmul outputs AND the named flash-attention output (a
                # custom-VJP kernel the dots policy can't see) are saved;
                # only elementwise work (norms, rotary, activations,
                # router softmax) is recomputed on the backward.
                policies = jax.checkpoint_policies
                fn = jax.checkpoint(
                    fn,
                    policy=policies.save_from_both_policies(
                        policies.checkpoint_dots,
                        policies.save_only_these_names("flash_attn_out"),
                    ),
                )
            else:
                # "full" (validate() rejects anything else): save layer
                # boundaries only.
                fn = jax.checkpoint(fn)
        return fn(layer_p, x)

    x, stats = lax.scan(body, x, stage_params)
    return x, stats


def _embed_tokens(embed, tokens, cfg):
    """Vocab-sharded embedding lookup: masked gather + psum('tp').

    A gather (XLA `take`, VJP = scatter-add) rather than a one-hot matmul:
    the matmul formulation costs 2*B*T*V_local*d FLOPs and materializes a
    [B, T, V_local] one-hot (0.5 GB at the flagship bench shape) per step —
    measurable single-chip MFU lost to work the FLOP accounting rightly
    excludes. Out-of-shard ids gather row 0 and are masked to zero, so the
    psum over tp reassembles exactly the one row each token owns."""
    v_local = embed.shape[0]
    start = lax.axis_index("tp") * v_local
    local_ids = tokens - start
    in_shard = jnp.logical_and(local_ids >= 0, local_ids < v_local)
    rows = jnp.take(embed, jnp.where(in_shard, local_ids, 0), axis=0)
    x = rows.astype(cfg.dtype) * in_shard[..., None].astype(cfg.dtype)
    return lax.psum(x, "tp")


def unembed_logits(params, xn, cfg):
    """Vocab-sharded logits from the final hidden states: the trained
    unembedding matrix, or the transposed embedding when tied."""
    if cfg.tie_embeddings:
        return jnp.einsum(
            "btd,vd->btv", xn.astype(cfg.dtype),
            params["embed"].astype(cfg.dtype),
        )
    return jnp.einsum(
        "btd,dv->btv", xn.astype(cfg.dtype),
        weight_cast(params["unembed"], cfg.dtype),
    )


def _sharded_softmax_xent(logits, targets, v_start, cfg):
    """Cross-entropy with a vocab-sharded logits tensor, plus the two
    standard large-model stability knobs:

    * `label_smoothing` eps: target distribution (1-eps)*one_hot + eps/V —
      the smoothed loss is lse - (1-eps)*tgt - eps*mean_v(logits), with the
      vocab mean psum'd across the tp shards.
    * `z_loss_coef`: + coef * lse^2 (ST-MoE/PaLM style), pulling the
      partition function toward 1 so bf16 logits can't drift.

    logits: [B, T, V_local] (local vocab shard), targets: [B, T] global ids.
    Returns per-token loss [B, T] (replicated over tp after the psums).
    """
    logits = logits.astype(jnp.float32)
    # The max shift is a numerical constant; stop_gradient keeps pmax out of
    # the backward graph (it has no differentiation rule, and needs none).
    local_max = jnp.max(lax.stop_gradient(logits), axis=-1)
    global_max = lax.pmax(local_max, "tp")
    sumexp = jnp.sum(jnp.exp(logits - global_max[..., None]), axis=-1)
    lse = jnp.log(lax.psum(sumexp, "tp")) + global_max

    v_local = logits.shape[-1]
    local_ids = targets - v_start
    in_shard = jnp.logical_and(local_ids >= 0, local_ids < v_local)
    # Gather the target logit instead of reducing against a [B, T, V_local]
    # one-hot (which costs a full-vocab f32 materialization + reduction per
    # step); the VJP is the matching scatter into the logits cotangent.
    tgt = jnp.take_along_axis(
        logits, jnp.where(in_shard, local_ids, 0)[..., None], axis=-1
    )[..., 0] * in_shard
    tgt = lax.psum(tgt, "tp")

    eps = cfg.label_smoothing
    if eps:
        vocab_mean = lax.psum(jnp.sum(logits, axis=-1), "tp") / cfg.vocab_size
        target_term = (1.0 - eps) * tgt + eps * vocab_mean
    else:
        target_term = tgt
    loss = lse - target_term
    if cfg.z_loss_coef:
        loss = loss + cfg.z_loss_coef * jnp.square(lse)
    return loss


# ---------------------------------------------------------------------------
# Top-level programs
# ---------------------------------------------------------------------------


def _run_pipeline(layers, x_mbs, cfg: TransformerConfig):
    """Dispatch the configured pipeline schedule over this rank's stacked
    layer shard. Returns (out [n_micro, mb, T_loc, d], aux_stats
    [lps, 2, E]) — the interleaved path's chunk-stacked aux flattens back
    to the same per-layer contract, so the loss-side pooling is schedule-
    agnostic (chunk-major slot order matches interleave_stage_params)."""
    stage_params = jax.tree.map(lambda a: a[0], layers)
    lps = jax.tree.leaves(stage_params)[0].shape[0]
    width = aux_stat_width(cfg)
    if cfg.pipeline_schedule == "interleaved":
        v = cfg.pipeline_virtual
        lpc = lps // v
        chunk_params = jax.tree.map(
            lambda a: a.reshape(v, lpc, *a.shape[1:]), stage_params
        )
        out, aux_stats = pipeline_apply_interleaved(
            partial(_stage_fn, cfg=cfg), chunk_params, x_mbs, v, "pp",
            with_aux=True,
            aux_init=jnp.zeros((lpc, 2, width), jnp.float32),
        )
        return out, aux_stats.reshape(lps, 2, width)
    return pipeline_apply(
        partial(_stage_fn, cfg=cfg), stage_params, x_mbs, "pp",
        with_aux=True,
        aux_init=jnp.zeros((lps, 2, width), jnp.float32),
    )


def _token_ce(params_view, xn, targets, cfg: TransformerConfig):
    """Per-token cross-entropy [B, T] from final hidden states, honoring
    `loss_chunk`: time chunks scan under jax.checkpoint so only
    [B, chunk, V_local] logits are ever resident (numerically exact — the
    loss is a per-token sum). `params_view` needs only the unembedding
    keys (`embed` when tied, else `unembed`) — the train paths pass the
    full param tree, the 1F1B head passes just its head slice."""
    b, t_local = xn.shape[0], xn.shape[1]

    def token_losses(xn_c, targets_c):
        logits = unembed_logits(params_view, xn_c, cfg)
        v_start = lax.axis_index("tp") * logits.shape[-1]
        return _sharded_softmax_xent(logits, targets_c, v_start, cfg)

    if cfg.loss_chunk and cfg.loss_chunk < t_local:
        if t_local % cfg.loss_chunk:
            raise ValueError(
                f"loss_chunk {cfg.loss_chunk} must divide the local "
                f"sequence length {t_local}"
            )
        nc = t_local // cfg.loss_chunk
        xn_c = jnp.moveaxis(
            xn.reshape(b, nc, cfg.loss_chunk, xn.shape[-1]), 1, 0
        )
        tg_c = jnp.moveaxis(targets.reshape(b, nc, cfg.loss_chunk), 1, 0)

        def body(_, ct):
            return None, jax.checkpoint(token_losses)(*ct)

        _, per_chunks = lax.scan(body, None, (xn_c, tg_c))
        return jnp.moveaxis(per_chunks, 0, 1).reshape(b, t_local)
    return token_losses(xn, targets)


def _local_loss_fn(params, inputs, targets, mask, cfg: TransformerConfig, n_micro):
    """Runs on each device's shards; returns (loss_sum, token_count,
    aux_mean) — aux_mean is the globally-averaged MoE balancing loss."""
    pp = lax.psum(1, "pp")
    x = _embed_tokens(params["embed"], inputs, cfg)  # [B_loc, T_loc, d]
    b_local = x.shape[0]
    if b_local % n_micro:
        raise ValueError(
            f"per-device batch {b_local} must be divisible by "
            f"n_microbatches {n_micro} (global batch % (dp * n_microbatches) == 0)"
        )
    x_mbs = x.reshape(n_micro, b_local // n_micro, *x.shape[1:])

    out, aux_stats = _run_pipeline(params["layers"], x_mbs, cfg)
    # out [n_micro, mb, T_loc, d]; aux_stats [lps, 2, E]
    out = out.reshape(b_local, *out.shape[2:])

    xn = rms_norm(out, params["final_norm"], cfg.norm_eps)
    per_token = _token_ce(params, xn, targets, cfg)

    is_last = lax.axis_index("pp") == pp - 1
    per_token = jnp.where(is_last, per_token * mask, 0.0)
    count = jnp.where(is_last, jnp.sum(mask), 0.0)

    # Sums reduce over ALL five mesh axes. The pipeline carry is promoted to
    # the full vma union of the stage weights — which includes 'tp' (and
    # 'ep' for MoE) — so every value here is typed varying over every axis
    # regardless of numeric replication; psumming over all of them is the
    # only way the result can satisfy an invariant (P()) out_spec. Axes the
    # value is numerically replicated on (tp always, ep on the dense path)
    # scale numerator and denominator equally, so the means are unchanged.
    def _reduce(x):
        x = pvary_to(x, frozenset({"dp", "sp", "pp", "ep", "tp"}))
        return lax.psum(x, ("dp", "sp", "pp", "ep", "tp"))

    # Aux (GShard, routed MoE only): each stage carried raw per-layer
    # [choice-count, gate-prob-sum] stats pooled over its active
    # microbatches; ONE fused psum over the token-sharding axes yields the
    # global-batch stats, from which each layer's E*sum(f_e*P_e) is formed
    # (f_e = fraction of routing choices picking expert e — counts sum to
    # k*n_tokens; P_e = mean gate probability). Pooling the linear stats
    # before the nonlinear product makes the objective identical on every
    # mesh shape AND microbatch count.
    if cfg.moe_top_k > 0:
        g = lax.psum(
            pvary_to(aux_stats, frozenset({"dp", "sp", "ep"})),
            ("dp", "sp", "ep"),
        )  # [lps, 2, E] global stats for this stage's layers
        choices, probs = g[:, 0, :], g[:, 1, :]
        total = jnp.maximum(jnp.sum(choices, -1, keepdims=True), 1e-9)
        frac = choices / total
        pbar = probs / jnp.maximum(total / cfg.moe_top_k, 1e-9)
        stage_aux = jnp.sum(cfg.n_experts * frac * pbar)
        # The pp psum in _reduce genuinely sums distinct stages (= all
        # n_layers layers); the dp/sp/ep/tp psums multiply the replicated
        # value by their product, divided back out here.
        groups = (
            lax.psum(1, "dp") * lax.psum(1, "sp") * lax.psum(1, "ep")
            * lax.psum(1, "tp")
        )
        aux_mean = _reduce(stage_aux) / (cfg.n_layers * groups)
    else:
        aux_mean = jnp.zeros((), jnp.float32)
    return _reduce(jnp.sum(per_token)), _reduce(count), aux_mean


def _local_grads_1f1b(params, inputs, targets, mask, cfg: TransformerConfig, n_micro):
    """1F1B training path: (loss, grads) via `pipeline_1f1b_grads`.

    The memory-capped schedule is not a differentiable forward, so this
    path cannot go through jax.value_and_grad — it assembles the full
    gradient tree from the primitive's per-rank pieces:

    * The embedding runs (and is differentiated) OUTSIDE the pipeline:
      its VJP closes over the fed-microbatch cotangents the primitive
      returns from rank 0.
    * The loss head (final norm + unembed + CE) runs INSIDE the last
      rank's backward phase, per microbatch, with the global 1/token
      normalization folded in (the token count is data-only, so it is
      known before the pipeline starts).
    * Reductions: the primitive promotes params to the loop's varying
      set, so each gradient leaf comes back UNREDUCED over exactly the
      axes the promotion added. Each leaf is psummed over (loop vma −
      its original vma) — the same reduction autodiff's pvary transpose
      would have inserted, paid once instead of per scan step.
    """
    pp = lax.psum(1, "pp")
    b_local = inputs.shape[0]
    if b_local % n_micro:
        raise ValueError(
            f"per-device batch {b_local} must be divisible by "
            f"n_microbatches {n_micro} (global batch % (dp * n_microbatches) == 0)"
        )
    mb = b_local // n_micro

    # Global (batch-wide) token count: data-only, so the per-microbatch
    # head can normalize by it up front. Replicated over pp/tp/ep.
    count = lax.psum(
        pvary_to(jnp.sum(mask), frozenset({"dp", "sp"})), ("dp", "sp")
    )
    scale = 1.0 / jnp.maximum(count, 1.0)

    x, embed_vjp = jax.vjp(
        lambda e: _embed_tokens(e, inputs, cfg), params["embed"]
    )
    x_mbs = x.reshape(n_micro, mb, *x.shape[1:])
    t_local = x.shape[1]
    mbt = targets.reshape(n_micro, mb, t_local)
    mbm = mask.reshape(n_micro, mb, t_local)

    stage_params = jax.tree.map(lambda a: a[0], params["layers"])

    def stage_plain(sp, xx):
        return _stage_fn(sp, xx, cfg=cfg)[0]

    head_params = {"final_norm": params["final_norm"]}
    if cfg.tie_embeddings:
        head_params["embed"] = params["embed"]
    else:
        head_params["unembed"] = params["unembed"]

    def head_fn(hp, y, b):
        xn = rms_norm(y, hp["final_norm"], cfg.norm_eps)
        tgt = lax.dynamic_index_in_dim(mbt, b, 0, keepdims=False)
        msk = lax.dynamic_index_in_dim(mbm, b, 0, keepdims=False)
        per_token = _token_ce(hp, xn, tgt, cfg)
        return jnp.sum(per_token * msk) * scale

    # tp (and ep, when MoE shards experts) are REPLICATION axes for the
    # loss value (every shard computes the same scalar after its internal
    # psums/gathers) — the primitive divides the objective by their sizes
    # so the device-summed objective is the true loss and the uniform
    # psum reduction below is exact. Axes absent from the loop's varying
    # set are ignored inside.
    loss, g_stage, g_head, dmb = pipeline_1f1b_grads(
        stage_plain, head_fn, stage_params, head_params, x_mbs, "pp",
        replicated_axes=("tp", "ep"),
    )

    # Per-leaf reduction: psum over exactly the axes the loop promoted
    # beyond the leaf's own varying set (dp/sp always; tp for leaves not
    # tp-sharded; pp for the replicated head/embed leaves).
    loop_vma = vma_union(g_stage, g_head, dmb)

    def _reduce_like(orig_tree, grad_tree):
        def red(o, g):
            missing = tuple(loop_vma - vma_union(o))
            return lax.psum(g, missing) if missing else g

        return jax.tree.map(red, orig_tree, grad_tree)

    g_stage = _reduce_like(stage_params, g_stage)
    g_head = _reduce_like(
        {k: params[k] for k in head_params}, g_head
    )

    # Fed-microbatch cotangents: partial per tp shard (the loop typed the
    # buffers tp-varying, so no transpose-psum ran) and pp-varying (zeros
    # off rank 0) — reduce both, then backprop the embedding.
    dmb = lax.psum(dmb, tuple(loop_vma - vma_union(x)))
    (g_embed,) = embed_vjp(dmb.reshape(b_local, t_local, x.shape[-1]))
    if cfg.tie_embeddings:
        g_embed = g_embed + g_head["embed"]

    grads = {
        "embed": g_embed,
        "layers": jax.tree.map(lambda g: g[None], g_stage),
        "final_norm": g_head["final_norm"],
    }
    if not cfg.tie_embeddings:
        grads["unembed"] = g_head["unembed"]

    # Loss: with the objective made globally consistent (1/|tp| inside
    # the primitive), one psum over its full varying set is the true
    # batch-mean loss.
    loss = lax.psum(loss, tuple(vma_union(loss)))
    return loss, grads


def build_train_step(
    config: TransformerConfig,
    mesh: Mesh,
    optimizer,
    opt_shardings=None,
    accum_steps: int = 1,
):
    """Returns jitted train_step(params, opt_state, batch) -> (params,
    opt_state, loss). Model runs under shard_map with explicit collectives;
    the elementwise optimizer update runs outside and inherits shardings.

    opt_shardings: optional NamedSharding tree for the optimizer state
    (see `parallel.zero.init_zero1_opt_state`) — constrains each step's
    new state onto it so Adam m/v stay physically sharded across `dp`
    (ZeRO-1) instead of replicated; XLA partitions the update and inserts
    the gather of the sharded parameter updates.

    accum_steps: gradient accumulation — the batch's leading dimension is
    split into `accum_steps` equal chunks run sequentially under
    `lax.scan`, their gradients averaged before ONE optimizer update.
    With equal-sized, fully-masked chunks this is numerically the
    full-batch step (differential-tested), at 1/accum_steps the
    activation memory."""
    cfg = config
    specs = param_specs(cfg)
    n_micro = cfg.n_microbatches or axis_size(mesh, "pp")

    def local_grads(params, inputs, targets, mask):
        if cfg.pipeline_schedule == "1f1b":
            # Memory-capped schedule: grads assembled from per-microbatch
            # VJPs (not a differentiable forward — see _local_grads_1f1b).
            return _local_grads_1f1b(params, inputs, targets, mask, cfg, n_micro)

        def scalar_loss(p):
            loss_sum, total, aux_mean = _local_loss_fn(
                p, inputs, targets, mask, cfg, n_micro
            )
            ce = loss_sum / jnp.maximum(total, 1.0)
            return ce + cfg.moe_aux_coef * aux_mean

        # No manual gradient psum: under shard_map's VMA typing, parameters
        # enter invariant over their replicated axes, every use inserts a
        # pvary, and the transpose of pvary IS the psum over those axes — so
        # AD returns fully-reduced gradients. Adding a manual psum here
        # would double-count (verified by differential test vs single-device).
        return jax.value_and_grad(scalar_loss)(params)

    sharded_grads = jax.shard_map(
        local_grads,
        mesh=mesh,
        in_specs=(specs, P("dp", "sp"), P("dp", "sp"), P("dp", "sp")),
        out_specs=(P(), specs),
    )

    @partial(jax.jit, donate_argnums=(0, 1))
    def train_step(params, opt_state, batch):
        mask = batch.get("mask")
        if mask is None:
            mask = jnp.ones_like(batch["targets"], jnp.float32)
        mask = mask.astype(jnp.float32)
        if accum_steps > 1:
            b = batch["inputs"].shape[0]
            if b % accum_steps:
                raise ValueError(
                    f"batch {b} not divisible by accum_steps {accum_steps}"
                )
            chunk = lambda a: a.reshape(accum_steps, b // accum_steps, *a.shape[1:])
            chunks = (chunk(batch["inputs"]), chunk(batch["targets"]), chunk(mask))

            def accum(carry, xs):
                inp, tgt, msk = xs
                loss_k, grads_k = sharded_grads(params, inp, tgt, msk)
                loss_acc, grads_acc = carry
                return (
                    loss_acc + loss_k,
                    jax.tree.map(jnp.add, grads_acc, grads_k),
                ), None

            zeros = jax.tree.map(jnp.zeros_like, params)
            (loss, grads), _ = lax.scan(
                accum, (jnp.zeros((), jnp.float32), zeros), chunks
            )
            inv = 1.0 / accum_steps
            loss = loss * inv
            grads = jax.tree.map(lambda g: g * inv, grads)
        else:
            loss, grads = sharded_grads(
                params, batch["inputs"], batch["targets"], mask
            )
        updates, new_opt_state = optimizer.update(grads, opt_state, params)
        if opt_shardings is not None:
            new_opt_state = jax.lax.with_sharding_constraint(
                new_opt_state, opt_shardings
            )
        new_params = jax.tree.map(
            lambda p, u: (p + u).astype(p.dtype), params, updates
        )
        # Full-mesh replication so every process of a multi-host gang holds
        # an addressable shard of the scalar (see mlp.build_train_step).
        loss = jax.lax.with_sharding_constraint(
            loss, NamedSharding(mesh, P())
        )
        return new_params, new_opt_state, loss

    return train_step


def build_eval_step(config: TransformerConfig, mesh: Mesh):
    """Jitted eval_step(params, batch) -> mean per-token cross-entropy,
    replicated. The loss-only half of `build_train_step` (same
    `_local_loss_fn`, same batch sharding contract, no grad/update) for
    held-out evaluation during training.

    Training-objective knobs (label smoothing, z-loss) are disabled for
    eval — standard practice, so exp(eval loss) stays a perplexity and
    curves are comparable across knob settings."""
    cfg = dc_replace(config, label_smoothing=0.0, z_loss_coef=0.0)
    specs = param_specs(cfg)
    n_micro = cfg.n_microbatches or axis_size(mesh, "pp")

    def local_loss(params, inputs, targets, mask):
        loss_sum, total, _ = _local_loss_fn(
            params, inputs, targets, mask, cfg, n_micro
        )
        return loss_sum / jnp.maximum(total, 1.0)

    sharded = jax.shard_map(
        local_loss,
        mesh=mesh,
        in_specs=(specs, P("dp", "sp"), P("dp", "sp"), P("dp", "sp")),
        out_specs=P(),
    )

    @jax.jit
    def eval_step(params, batch):
        mask = batch.get("mask")
        if mask is None:
            mask = jnp.ones_like(batch["targets"], jnp.float32)
        loss = sharded(
            params, batch["inputs"], batch["targets"],
            mask.astype(jnp.float32),
        )
        return jax.lax.with_sharding_constraint(loss, NamedSharding(mesh, P()))

    return eval_step


def build_forward(config: TransformerConfig, mesh: Mesh):
    """Jitted forward(params, tokens) -> logits [B, T, vocab] (tp-gathered).
    Used for evaluation and the single-chip entry point."""
    cfg = config
    specs = param_specs(cfg)
    n_micro = cfg.n_microbatches or axis_size(mesh, "pp")

    def local_forward(params, tokens):
        pp = lax.psum(1, "pp")
        x = _embed_tokens(params["embed"], tokens, cfg)
        b_local = x.shape[0]
        # Largest microbatch count <= n_micro that divides the local batch
        # (forward tolerates any batch; training enforces divisibility).
        mb_count = next(m for m in range(min(n_micro, b_local), 0, -1) if b_local % m == 0)
        x_mbs = x.reshape(mb_count, b_local // mb_count, *x.shape[1:])
        out, _ = _run_pipeline(params["layers"], x_mbs, cfg)
        out = out.reshape(b_local, *out.shape[2:])
        # Broadcast the last stage's result to every pp rank.
        is_last = lax.axis_index("pp") == pp - 1
        out = lax.psum(jnp.where(is_last, out, 0.0), "pp")
        xn = rms_norm(out, params["final_norm"], cfg.norm_eps)
        # Vocab stays sharded; the out_spec concatenates the tp shards into
        # the global [B, T, vocab] array — no gather collective needed.
        logits = unembed_logits(params, xn, cfg)
        # MoE leaves the activations *typed* ep-varying (the routed path's
        # all_gather replicates values but, unlike psum, keeps the axis in
        # the vma set), which the P("dp","sp","tp") out_spec rejects. A
        # pmean over the residual axes is numerically the identity on the
        # replicated value and retypes it invariant.
        extra = tuple(vma_union(logits) - frozenset({"dp", "sp", "tp"}))
        if extra:
            logits = lax.pmean(logits, extra)
        return logits

    return jax.jit(
        jax.shard_map(
            local_forward,
            mesh=mesh,
            in_specs=(specs, P("dp", "sp")),
            out_specs=P("dp", "sp", "tp"),
        )
    )
