"""ResNet-style CNN, mesh-first — the vision model family.

Capability mapping: the reference orchestrates CNN training from outside
(`examples/pytorch/cnn-mnist`, `examples/pytorch/resnet-cifar10` run
torchvision models under torchrun DDP); this is the TPU-native in-framework
equivalent the workload runner executes directly.

Design, TPU-first rather than a torch translation:

* NHWC layout with `lax.conv_general_dilated` — XLA's native conv layout on
  TPU, tiled straight onto the MXU; compute in bfloat16, f32 parameters.
* GroupNorm instead of BatchNorm: normalization is batch-independent, so
  data-parallel shards need no cross-device batch statistics (BatchNorm's
  running-stats all-reduce is a torch-ism the mesh doesn't need).
* Parallelism via `jax.jit` + `NamedSharding`: images/labels are sharded
  over the `dp` mesh axis, parameters are replicated, and XLA's SPMD
  partitioner inserts the gradient all-reduce — the compiler-driven
  counterpart to the transformer's explicit-collective `shard_map` style
  (both idioms are first-class in this framework).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Any

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


@dataclass(frozen=True)
class CNNConfig:
    num_classes: int = 10
    in_channels: int = 3
    widths: tuple = (32, 64, 128)  # channels per stage; stride-2 between
    blocks_per_stage: int = 2
    groups: int = 8  # GroupNorm groups (must divide every width)
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32

    def validate(self) -> None:
        for w in self.widths:
            if w % self.groups:
                raise ValueError(
                    f"GroupNorm groups {self.groups} must divide width {w}"
                )


def _conv_init(key, kh, kw, cin, cout, dtype):
    fan_in = kh * kw * cin
    return jax.random.normal(key, (kh, kw, cin, cout), dtype) * np.sqrt(
        2.0 / fan_in
    )


def init_params(rng: jax.Array, config: CNNConfig) -> dict:
    cfg = config
    cfg.validate()
    keys = iter(jax.random.split(rng, 4 + 4 * len(cfg.widths) * cfg.blocks_per_stage))
    params: dict = {
        "stem": _conv_init(next(keys), 3, 3, cfg.in_channels, cfg.widths[0], cfg.param_dtype),
        "stem_scale": jnp.ones((cfg.widths[0],), cfg.param_dtype),
        "stem_bias": jnp.zeros((cfg.widths[0],), cfg.param_dtype),
        "stages": [],
    }
    cin = cfg.widths[0]
    for s, width in enumerate(cfg.widths):
        stage = []
        for b in range(cfg.blocks_per_stage):
            block = {
                "conv1": _conv_init(next(keys), 3, 3, cin if b == 0 else width, width, cfg.param_dtype),
                "scale1": jnp.ones((width,), cfg.param_dtype),
                "bias1": jnp.zeros((width,), cfg.param_dtype),
                "conv2": _conv_init(next(keys), 3, 3, width, width, cfg.param_dtype),
                "scale2": jnp.ones((width,), cfg.param_dtype),
                "bias2": jnp.zeros((width,), cfg.param_dtype),
            }
            # First block of every stage after the first downsamples
            # (stride 2), so its shortcut needs a projection even when the
            # width is unchanged; stage 0 projects only on a width change.
            if b == 0 and (s > 0 or cin != width):
                block["proj"] = _conv_init(next(keys), 1, 1, cin, width, cfg.param_dtype)
            stage.append(block)
        params["stages"].append(stage)
        cin = width
    params["head"] = jax.random.normal(
        next(keys), (cfg.widths[-1], cfg.num_classes), cfg.param_dtype
    ) / np.sqrt(cfg.widths[-1])
    params["head_bias"] = jnp.zeros((cfg.num_classes,), cfg.param_dtype)
    return params


def _conv(x, w, stride=1):
    return lax.conv_general_dilated(
        x,
        w.astype(x.dtype),
        window_strides=(stride, stride),
        padding="SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )


def _group_norm(x, scale, bias, groups, eps=1e-5):
    n, h, w, c = x.shape
    x32 = x.astype(jnp.float32).reshape(n, h, w, groups, c // groups)
    mean = jnp.mean(x32, axis=(1, 2, 4), keepdims=True)
    var = jnp.var(x32, axis=(1, 2, 4), keepdims=True)
    x32 = (x32 - mean) * lax.rsqrt(var + eps)
    x32 = x32.reshape(n, h, w, c)
    return (x32 * scale.astype(jnp.float32) + bias.astype(jnp.float32)).astype(
        x.dtype
    )


def _block(p, x, cfg: CNNConfig, stride: int):
    shortcut = _conv(x, p["proj"], stride) if "proj" in p else x
    y = _conv(x, p["conv1"], stride)
    y = jax.nn.relu(_group_norm(y, p["scale1"], p["bias1"], cfg.groups))
    y = _conv(y, p["conv2"])
    y = _group_norm(y, p["scale2"], p["bias2"], cfg.groups)
    return jax.nn.relu(shortcut + y)


def forward(params, images, config: CNNConfig):
    """images: [B, H, W, C] float; returns logits [B, num_classes]."""
    cfg = config
    x = images.astype(cfg.dtype)
    x = _conv(x, params["stem"])
    x = jax.nn.relu(
        _group_norm(x, params["stem_scale"], params["stem_bias"], cfg.groups)
    )
    for s, stage in enumerate(params["stages"]):
        for b, block in enumerate(stage):
            x = _block(block, x, cfg, stride=2 if (b == 0 and s > 0) else 1)
    x = jnp.mean(x.astype(jnp.float32), axis=(1, 2))  # global average pool
    return x @ params["head"].astype(jnp.float32) + params["head_bias"]


def build_train_step(config: CNNConfig, mesh: Mesh, optimizer):
    """Jitted data-parallel train step: batch sharded over `dp`, parameters
    replicated; XLA SPMD inserts the gradient all-reduce."""
    cfg = config
    batch_sharding = NamedSharding(mesh, P("dp"))

    def loss_fn(params, images, labels):
        logits = forward(params, images, cfg)
        losses = -jax.nn.log_softmax(logits)[
            jnp.arange(labels.shape[0]), labels
        ]
        return jnp.mean(losses)

    @partial(jax.jit, donate_argnums=(0, 1))
    def train_step(params, opt_state, batch):
        images = lax.with_sharding_constraint(batch["images"], batch_sharding)
        labels = lax.with_sharding_constraint(batch["labels"], batch_sharding)
        loss, grads = jax.value_and_grad(loss_fn)(params, images, labels)
        updates, new_opt_state = optimizer.update(grads, opt_state, params)
        new_params = jax.tree.map(
            lambda p, u: (p + u).astype(p.dtype), params, updates
        )
        # Full-mesh replication so every process of a multi-host gang holds
        # an addressable shard of the scalar (see mlp.build_train_step).
        loss = lax.with_sharding_constraint(
            loss, NamedSharding(mesh, P())
        )
        return new_params, new_opt_state, loss

    return train_step
