"""The consistency checker: four invariants over a recorded history.

``check_history`` is pure — it consumes a `HistoryRecorder` snapshot (or
any list of op dicts of that shape) plus the run's final durable state
and returns a `CheckReport`; nothing here touches the cluster, the
network, or the wall clock, so the same history always yields the same
verdict and the report is safely byte-comparable across seeded runs.

Invariant 4 is a Wing & Gong linearizability search over the single
register's operations: depth-first over the concurrent frontier (ops
whose invocation precedes every pending op's response), memoized on
(pending set, register value). A successful read that observed the
register ABSENT participates as an observation of the initial value —
a stale replica serving pre-creation state after an acknowledged create
is a linearizability violation, not a skippable gap. Writes that answered with a quorum
Warning — or whose connection died with the outcome unknown — are
*indeterminate*: the search may apply them or drop them (lost on the
minority side), never both. Histories here are nearly sequential (the
scenario drivers are), so the frontier stays tiny; `MAX_WINDOW` guards
the exponential worst case and reports an over-wide history as
uncheckable rather than hanging.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

# Concurrent-frontier bound for the linearizability search: scenario
# drivers are sequential per session, so real frontiers hold a handful of
# ops; past this the search refuses (reported, not silently skipped).
MAX_WINDOW = 16


@dataclass
class CheckReport:
    """Machine-checked verdict over one history."""

    ok: bool = True
    invariants: dict = field(default_factory=dict)
    violations: list = field(default_factory=list)
    stats: dict = field(default_factory=dict)

    def to_dict(self) -> dict:
        return {
            "ok": self.ok,
            "invariants": self.invariants,
            "violations": self.violations,
            "stats": self.stats,
        }

    def _fail(self, invariant: str, message: str, **detail) -> None:
        self.ok = False
        self.invariants[invariant]["ok"] = False
        self.violations.append(
            {"invariant": invariant, "message": message, **detail}
        )


def _completed(op: dict) -> bool:
    return op.get("response") is not None


def _write_applied_maybe(op: dict) -> bool:
    """Could this write have taken effect without a clean majority ack?
    True for quorum-Warning 2xx acks (applied on a minority, may be
    lost) and for unknown outcomes (no response / connection died); an
    explicit HTTP error status means the server never applied it."""
    if op.get("acked"):
        return False  # definite, not maybe
    if op.get("ok"):
        return True  # 2xx with Warning: applied somewhere, not durable
    return op.get("status") is None  # outcome unknown


def check_history(
    ops: list[dict],
    final_state: Optional[dict] = None,
    register_key: Optional[str] = None,
    initial_value: Optional[str] = None,
) -> CheckReport:
    """Prove the four consistency invariants over `ops`.

    final_state: {object key: final value-or-None} of the surviving
    leader's durable state (value matters only for the register).
    register_key: the single-object register whose ops are linearized.
    """
    report = CheckReport()
    writes = [op for op in ops if op["kind"] == "write"]
    reads = [op for op in ops if op["kind"] == "read"]
    acked = [op for op in writes if op.get("acked")]
    report.stats = {
        "ops": len(ops),
        "writes": len(writes),
        "reads": len(reads),
        "acked_writes": len(acked),
        "indeterminate_writes": sum(
            1 for op in writes if _write_applied_maybe(op)
        ),
        "failed_ops": sum(
            1 for op in ops if _completed(op) and not op.get("ok")
        ),
    }

    _check_durability(report, acked, final_state, register_key,
                      initial_value, writes)
    _check_leader_per_term(report, writes)
    _check_session_monotonic(report, ops)
    _check_linearizable(report, ops, register_key, initial_value)
    return report


# -- invariant 1: no majority-acked write is ever lost -----------------------


def _check_durability(report, acked, final_state, register_key,
                      initial_value, writes) -> None:
    report.invariants["durability"] = {"ok": True, "checked": len(acked)}
    if final_state is None:
        report.invariants["durability"]["checked"] = 0
        return
    for op in acked:
        if op["key"] not in final_state:
            report._fail(
                "durability",
                f"majority-acked write of {op['key']} (op {op['id']}) "
                f"is absent from the final state — an acknowledged "
                f"write was LOST",
                op=op["id"], key=op["key"],
            )
    if register_key is None or register_key not in final_state:
        return
    final_value = final_state[register_key]
    acked_reg = [op for op in acked if op["key"] == register_key]
    if not acked_reg:
        return
    # Register staleness: the final value must be AT LEAST as new as the
    # newest acknowledged write (a later indeterminate write landing is
    # fine — that overwrote, it did not lose).
    order = {op["value"]: op["id"] for op in writes
             if op["key"] == register_key and op["value"] is not None}
    newest_acked = max(acked_reg, key=lambda op: op["id"])
    if final_value == initial_value and final_value not in order:
        report._fail(
            "durability",
            f"register {register_key} ended at its initial value but "
            f"write op {newest_acked['id']} "
            f"(value {newest_acked['value']!r}) was majority-acked",
            op=newest_acked["id"], key=register_key,
        )
        return
    final_writer = order.get(final_value)
    if final_writer is None:
        report._fail(
            "durability",
            f"register {register_key} ended at {final_value!r}, a value "
            f"no recorded write produced",
            key=register_key,
        )
    elif final_writer < newest_acked["id"]:
        report._fail(
            "durability",
            f"register {register_key} ended at {final_value!r} (op "
            f"{final_writer}) — OLDER than majority-acked op "
            f"{newest_acked['id']} (value {newest_acked['value']!r}): an "
            f"acknowledged write was rolled back",
            op=newest_acked["id"], key=register_key,
        )


# -- invariant 2: at most one unfenced leader serves writes per term ---------


def _check_leader_per_term(report, writes) -> None:
    served: dict[int, set] = {}
    for op in writes:
        if op.get("ok") and op.get("term") is not None and op.get("replica"):
            served.setdefault(op["term"], set()).add(op["replica"])
    report.invariants["leader_per_term"] = {
        "ok": True, "terms": len(served),
    }
    for term, replicas in sorted(served.items()):
        if len(replicas) > 1:
            report._fail(
                "leader_per_term",
                f"term {term} saw writes served by "
                f"{sorted(replicas)} — more than one unfenced leader "
                f"accepted writes in one epoch",
                term=term, replicas=sorted(replicas),
            )


# -- invariant 3: per-session reads are monotonic in resourceVersion --------


def _check_session_monotonic(report, ops) -> None:
    checked = 0
    floors: dict[str, tuple[int, int]] = {}  # session -> (rv floor, op id)
    for op in sorted(
        (o for o in ops if _completed(o) and o.get("ok")
         and o.get("rv") is not None),
        key=lambda o: o["response"],
    ):
        checked += 1
        session = op["session"]
        floor = floors.get(session)
        if floor is not None and op["rv"] < floor[0]:
            report.invariants.setdefault(
                "session_monotonic", {"ok": True, "checked": 0}
            )
            report._fail(
                "session_monotonic",
                f"session {session} observed resourceVersion "
                f"{op['rv']} (op {op['id']}) after already seeing "
                f"{floor[0]} (op {floor[1]}) — a stale replica served "
                f"state the session had outrun",
                op=op["id"], session=session,
            )
        if floor is None or op["rv"] > floor[0]:
            floors[session] = (op["rv"], op["id"])
    inv = report.invariants.setdefault("session_monotonic", {"ok": True})
    inv["checked"] = checked


# -- invariant 4: the single-object register linearizes ----------------------


def _check_linearizable(report, ops, register_key, initial_value) -> None:
    inv = {"ok": True, "checked": 0}
    report.invariants["linearizable"] = inv
    if register_key is None:
        return
    entries = []
    for op in ops:
        if op["key"] != register_key:
            continue
        if op["kind"] == "write":
            if _completed(op) and not op.get("ok") and \
                    op.get("status") is not None:
                continue  # cleanly rejected: never applied
            entries.append({
                "id": op["id"], "kind": "write", "value": op["value"],
                "inv": op["invoke"],
                "res": op["response"] if _completed(op) else None,
                "maybe": _write_applied_maybe(op),
            })
        elif op.get("ok"):
            # value None = the read observed the register ABSENT — a
            # real observation (it must linearize before every applied
            # create), not a gap in the history: a stale replica serving
            # pre-creation state after an acked write must fail here.
            entries.append({
                "id": op["id"], "kind": "read", "value": op["value"],
                "inv": op["invoke"], "res": op["response"],
                "maybe": False,
            })
    inv["checked"] = len(entries)
    if not entries:
        return
    verdict = _wing_gong(entries, initial_value)
    if verdict == "window":
        report._fail(
            "linearizable",
            f"register {register_key}: concurrent window exceeded "
            f"{MAX_WINDOW} ops — history too wide to check",
            key=register_key,
        )
    elif not verdict:
        report._fail(
            "linearizable",
            f"register {register_key}: no legal linearization exists "
            f"over its {len(entries)} operations — a read observed a "
            f"value no consistent order of the writes can explain",
            key=register_key,
            ops=[e["id"] for e in entries],
        )


# -- cross-shard mode (docs/sharding.md) -------------------------------------


def check_sharded_history(
    ops: list[dict],
    shard_of,
    final_states: Optional[dict] = None,
    register_keys: Optional[dict] = None,
    initial_value: Optional[str] = None,
    memberships: Optional[dict] = None,
) -> CheckReport:
    """The checker generalized to a sharded control plane: per-shard
    guarantees plus cross-shard session monotonicity through the router.

    ``shard_of(op)`` maps each op to its scope: an int shard id (the op
    targeted one shard's keyspace — directly or via the front door's
    per-key dispatch), or the string ``"router"`` for cross-shard
    operations (merged LISTs/watches) whose resourceVersions are ROUTER
    rvs. Scopes must not mix: shard rvs and router rvs are different
    counters, and a monotonicity check across them would be comparing
    clocks.

    Per shard: all four single-quorum invariants (durability against
    that shard's final state, one unfenced leader per term,
    session-monotonic shard rvs, register linearizability over
    ``register_keys[shard]``), reported under ``shard{N}:{invariant}``.
    Router scope: session monotonicity over router rvs — the cross-shard
    guarantee the merged journal's single rv counter exists to provide
    (a session that saw merged position R may never be served merged
    state older than R, whichever shards contributed).

    The combined report is green only when every sub-invariant holds —
    so a fence-disabled run that lets one shard's deposed leader serve a
    stale read fails THIS checker too (the teeth contract of
    docs/sharding.md).

    ``memberships`` (optional): shard -> ordered list of voting sets
    (each a list of replica ids — ``ReplicaSet.membership_log`` or the
    recovered ``Store.membership_log``). Two membership-aware quorum
    invariants are proven per shard (docs/sharding.md "Replica
    migration"): **membership-single-change** — consecutive voting sets
    differ by exactly one replica (the joint-consensus walk never jumps
    configurations) — and **membership-quorum-overlap** — for every
    consecutive pair, a majority of the old set plus a majority of the
    new exceeds their union (any two quorums across the change share a
    replica, so no two leaders can commit disjoint histories mid-move)."""
    report = CheckReport()
    scopes: dict = {}
    for op in ops:
        scopes.setdefault(shard_of(op), []).append(op)
    router_ops = scopes.pop("router", [])
    for shard in sorted(scopes):
        sub = check_history(
            scopes[shard],
            final_state=(final_states or {}).get(shard),
            register_key=(register_keys or {}).get(shard),
            initial_value=initial_value,
        )
        for name, verdict in sub.invariants.items():
            report.invariants[f"shard{shard}:{name}"] = verdict
        for violation in sub.violations:
            report.violations.append({**violation, "shard": shard})
        if not sub.ok:
            report.ok = False
        for key, value in sub.stats.items():
            report.stats[key] = report.stats.get(key, 0) + value
    for shard in sorted(memberships or {}):
        _check_memberships(report, shard, memberships[shard])
    _check_session_monotonic(router_report := CheckReport(), router_ops)
    verdict = router_report.invariants.get(
        "session_monotonic", {"ok": True, "checked": 0}
    )
    report.invariants["cross_shard_session_monotonic"] = verdict
    for violation in router_report.violations:
        report.violations.append({**violation, "shard": "router",
                                  "invariant": "cross_shard_session_monotonic"})
    if not router_report.ok:
        report.ok = False
    report.stats["router_ops"] = len(router_ops)
    report.stats["shards"] = len(scopes)
    return report


def _check_memberships(report: CheckReport, shard, sets: list) -> None:
    """Membership-aware quorum accounting over one shard's voting-set
    history (see check_sharded_history)."""
    checked = max(0, len(sets) - 1)
    single_name = f"shard{shard}:membership-single-change"
    overlap_name = f"shard{shard}:membership-quorum-overlap"
    report.invariants[single_name] = {"ok": True, "checked": checked}
    report.invariants[overlap_name] = {"ok": True, "checked": checked}
    for i in range(1, len(sets)):
        old, new = set(sets[i - 1]), set(sets[i])
        if len(old ^ new) != 1:
            report._fail(
                single_name,
                f"voting sets {sorted(old)} -> {sorted(new)} change "
                f"{len(old ^ new)} replicas at once — the joint-consensus "
                f"walk must move exactly one replica per step",
                shard=shard, index=i,
            )
        maj_old = len(old) // 2 + 1
        maj_new = len(new) // 2 + 1
        if maj_old + maj_new <= len(old | new):
            report._fail(
                overlap_name,
                f"voting sets {sorted(old)} -> {sorted(new)}: majorities "
                f"({maj_old}+{maj_new}) do not overlap across the union "
                f"of {len(old | new)} replicas — two disjoint quorums "
                f"could commit divergent histories mid-change",
                shard=shard, index=i,
            )


def _wing_gong(entries, initial_value):
    """Wing & Gong search; True / False / "window" (frontier too wide).

    An op joins the frontier once its invocation precedes every pending
    op's response; a pending set without reads is always completable
    (writes order by invocation), and an indeterminate write may be
    dropped (lost) instead of applied."""
    inf = float("inf")
    res = [inf if e["res"] is None else e["res"] for e in entries]
    frontier_overflow = [False]
    seen: set = set()

    def solve(pending: frozenset, value) -> bool:
        if not any(entries[i]["kind"] == "read" for i in pending):
            return True
        key = (pending, value)
        if key in seen:
            return False
        seen.add(key)
        min_res = min(res[i] for i in pending)
        frontier = [i for i in sorted(pending)
                    if entries[i]["inv"] <= min_res]
        if len(frontier) > MAX_WINDOW:
            frontier_overflow[0] = True
            return False
        for i in frontier:
            e = entries[i]
            rest = pending - {i}
            if e["kind"] == "read":
                if e["value"] == value and solve(rest, value):
                    return True
            else:
                if solve(rest, e["value"]):
                    return True
                if e["maybe"] and solve(rest, value):
                    return True  # dropped: lost on the minority side
        return False

    ok = solve(frozenset(range(len(entries))), initial_value)
    if frontier_overflow[0] and not ok:
        return "window"
    return ok


__all__ = [
    "CheckReport",
    "MAX_WINDOW",
    "check_history",
    "check_sharded_history",
]
