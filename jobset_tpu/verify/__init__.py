"""Jepsen-style consistency verification for the replicated control plane.

`history` records the client-visible side of a run — every invoke/response
pair of create/update/read operations, stamped with the serving replica's
term and identity (the `X-Jobset-Term` / `X-Jobset-Replica` headers a
replicated server emits) — on a logical clock, never the wall clock, so
two seeded runs record byte-identical histories. `checker` proves four
invariants over any recorded history (docs/ha.md "Consistency
guarantees"):

1. **Durability** — no majority-acknowledged write is ever lost: every
   clean-acked (2xx, no Warning) write's object is present in the final
   state, and the register's final value is never older than the newest
   acknowledged write.
2. **Leader uniqueness** — at most one unfenced leader serves writes per
   term.
3. **Session monotonicity** — within one client session, observed
   resourceVersions never go backwards (a replica cannot serve a read
   older than what the session already saw).
4. **Linearizability** — operations on the single-object register admit
   a legal linearization (a small-window Wing–Gong search; writes that
   answered with a quorum Warning are *indeterminate* — they may take
   effect or be lost, never both).

The partition scenarios (`chaos/scenarios.py`) run the checker as their
acceptance gate; a deliberately fence-disabled run FAILS it, which is the
proof the checker has teeth.

`check_sharded_history` generalizes the checker to the sharded control
plane (docs/sharding.md): every invariant per shard (each shard group is
its own quorum with its own rv counter) plus cross-shard session
monotonicity over the front door's merged-journal rvs — the seeded
region-cut scenario runs it as the gate, and its fence-disabled run
fails it too.
"""

from .checker import CheckReport, check_history, check_sharded_history
from .history import HistoryRecorder

__all__ = [
    "CheckReport",
    "HistoryRecorder",
    "check_history",
    "check_sharded_history",
]
