"""Bounded, wall-clock-free history recorder for the consistency checker.

One `HistoryRecorder` captures the client-visible half of a run: each
operation is an *invoke* (session, kind, key, intended value) followed by
a *response* (ok, observed value, resourceVersion, serving term/replica,
whether the write was majority-acknowledged). Time is a logical counter
bumped once per invoke and once per response — the recorded order IS the
real-time order the checker's linearizability window uses, and because no
wall clock is read, two seeded runs that perform the same operations
record byte-identical histories (the scenarios' byte-identity gate
compares the `normalized()` form, which additionally maps raw fencing
terms to dense first-appearance indices: term VALUES depend on how many
failed lease acquisitions a partition produced — timing — while the term
STRUCTURE, which writes shared an epoch, is deterministic).
"""

from __future__ import annotations

import threading
from typing import Optional


class HistoryRecorder:
    """Append-only operation history on a logical clock.

    ``invoke`` returns an op id; ``complete`` closes it. An op left
    incomplete (driver crashed mid-call, connection died with the outcome
    unknown) keeps ``response: None`` — the checker treats such writes as
    indeterminate, exactly like a quorum-Warning ack.
    """

    MAX_OPS = 100_000  # bounded, but big enough for any scenario storm

    def __init__(self):
        self._lock = threading.Lock()
        self.ops: list[dict] = []
        self._time = 0
        self._by_id: dict[int, dict] = {}

    def invoke(self, session: str, kind: str, key: str,
               value: Optional[str] = None) -> int:
        """Start one operation (`kind` is "write" or "read"); returns the
        op id for `complete`. `value` is a write's intended value."""
        with self._lock:
            self._time += 1
            op_id = len(self.ops)
            op = {
                "id": op_id,
                "session": session,
                "kind": kind,
                "key": key,
                "value": value,
                "invoke": self._time,
                "response": None,
                "ok": None,
                "status": None,
                "rv": None,
                "term": None,
                "replica": None,
                "acked": False,
            }
            if len(self.ops) < self.MAX_OPS:
                self.ops.append(op)
                self._by_id[op_id] = op
            return op_id

    def complete(
        self,
        op_id: int,
        ok: bool,
        status: Optional[int] = None,
        value: Optional[str] = None,
        rv: Optional[int] = None,
        term: Optional[int] = None,
        replica: Optional[str] = None,
        acked: bool = False,
    ) -> None:
        """Close an operation: `ok` = the server answered 2xx; `acked` =
        a write's clean majority acknowledgement (2xx AND no Warning
        header — the durable contract); `value` is a read's observed
        value; `term`/`replica` come from the response's replication
        identity headers."""
        with self._lock:
            op = self._by_id.get(op_id)
            if op is None:
                return
            self._time += 1
            op["response"] = self._time
            op["ok"] = bool(ok)
            op["status"] = status
            if value is not None:
                op["value"] = value
            op["rv"] = rv
            op["term"] = term
            op["replica"] = replica
            op["acked"] = bool(acked)

    # -- views --------------------------------------------------------------

    def snapshot(self) -> list[dict]:
        with self._lock:
            return [dict(op) for op in self.ops]

    def normalized(self) -> list[dict]:
        """Snapshot with raw fencing terms mapped to dense indices in
        first-appearance order — the byte-identity form (see module
        docstring for why raw term values are timing-dependent)."""
        dense: dict[int, int] = {}
        out = []
        for op in self.snapshot():
            term = op["term"]
            if term is not None:
                op["term"] = dense.setdefault(term, len(dense))
            out.append(op)
        return out


__all__ = ["HistoryRecorder"]
