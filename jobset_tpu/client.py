"""Typed Python client for the controller server.

Analog of the reference's generated clients (`client-go/` typed clientset
and the OpenAPI Python SDK, `sdk/python/README.md:1-10`) — but hand-written
against the controller's REST surface, returning the same `JobSet` dataclass
types the rest of the framework uses instead of a parallel generated model
hierarchy.  stdlib-only (urllib), so user containers need no extra deps to
talk to the control plane.
"""

from __future__ import annotations

import json
import time
import urllib.error
import urllib.request
from typing import Optional

from .api import serialization
from .api.types import JobSet


class ApiError(Exception):
    def __init__(self, status: int, message: str):
        super().__init__(f"HTTP {status}: {message}")
        self.status = status
        self.message = message


class JobSetClient:
    """Client bound to one controller server (`http://host:port`)."""

    API = "/apis/jobset.x-k8s.io/v1alpha2"

    def __init__(self, base_url: str, timeout: float = 30.0):
        if "://" not in base_url:
            base_url = f"http://{base_url}"
        self.base_url = base_url.rstrip("/")
        self.timeout = timeout

    # -- transport --------------------------------------------------------

    def _request(self, method: str, path: str, body: Optional[bytes] = None,
                 content_type: str = "application/json"):
        req = urllib.request.Request(
            self.base_url + path,
            data=body,
            method=method,
            headers={"Content-Type": content_type} if body is not None else {},
        )
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                data = resp.read()
                ctype = resp.headers.get("Content-Type", "")
        except urllib.error.HTTPError as exc:
            detail = exc.read().decode(errors="replace")
            try:
                detail = json.loads(detail).get("error", detail)
            except (json.JSONDecodeError, AttributeError):
                pass
            raise ApiError(exc.code, detail) from None
        if ctype.startswith("application/json"):
            return json.loads(data)
        return data.decode()

    # -- jobsets ----------------------------------------------------------

    def _collection(self, namespace: str) -> str:
        return f"{self.API}/namespaces/{namespace}/jobsets"

    def create(self, js: JobSet | dict | str, namespace: Optional[str] = None) -> JobSet:
        """Create from a JobSet object, a manifest dict, or YAML text.

        Namespace resolution mirrors kubectl: an explicit `namespace`
        argument wins, else the manifest's own namespace, else "default".
        The server rejects a manifest whose namespace disagrees with the
        request path.
        """
        if isinstance(js, JobSet):
            manifest_ns = js.metadata.namespace
            body = serialization.to_yaml(js).encode()
        elif isinstance(js, dict):
            manifest_ns = (js.get("metadata") or {}).get("namespace")
            body = json.dumps(js).encode()
        else:
            import yaml as _yaml

            manifest_ns = ((_yaml.safe_load(js) or {}).get("metadata") or {}).get(
                "namespace"
            )
            body = js.encode()
        ns = namespace or manifest_ns or "default"
        out = self._request("POST", self._collection(ns), body,
                            content_type="application/yaml")
        return serialization.from_dict(out)

    def apply_yaml(self, text: str, namespace: Optional[str] = None) -> list[JobSet]:
        """Create every document in a (possibly multi-doc) YAML stream; each
        document's own metadata.namespace wins over the `namespace` arg."""
        import yaml as _yaml

        created = []
        for doc in _yaml.safe_load_all(text):
            if not doc:
                continue
            doc_ns = (doc.get("metadata") or {}).get("namespace")
            created.append(self.create(doc, namespace=doc_ns or namespace))
        return created

    def get(self, name: str, namespace: str = "default") -> JobSet:
        out = self._request("GET", f"{self._collection(namespace)}/{name}")
        return serialization.from_dict(out)

    def get_raw(self, name: str, namespace: str = "default") -> dict:
        """Manifest dict including status (the wire representation)."""
        return self._request("GET", f"{self._collection(namespace)}/{name}")

    def list(self, namespace: str = "default") -> list[JobSet]:
        return [serialization.from_dict(item) for item in self.list_raw(namespace)]

    def list_raw(self, namespace: str = "default") -> list[dict]:
        """Manifest dicts (status included) in one request — what the
        collection endpoint already serves; no per-item round-trips."""
        return self._request("GET", self._collection(namespace))["items"]

    def update(self, js: JobSet, namespace: Optional[str] = None) -> JobSet:
        ns = namespace or js.metadata.namespace or "default"
        body = serialization.to_yaml(js).encode()
        out = self._request("PUT", f"{self._collection(ns)}/{js.metadata.name}", body,
                            content_type="application/yaml")
        return serialization.from_dict(out)

    def delete(self, name: str, namespace: str = "default") -> None:
        self._request("DELETE", f"{self._collection(namespace)}/{name}")

    def suspend(self, name: str, namespace: str = "default") -> JobSet:
        js = self.get(name, namespace)
        js.spec.suspend = True
        return self.update(js, namespace)

    def resume(self, name: str, namespace: str = "default") -> JobSet:
        js = self.get(name, namespace)
        js.spec.suspend = False
        return self.update(js, namespace)

    def wait_for_condition(
        self,
        name: str,
        condition_type: str,
        namespace: str = "default",
        timeout: float = 60.0,
        poll: float = 0.2,
    ) -> dict:
        """Poll until the JobSet has `condition_type` with status True;
        returns the condition dict. The watch analog for a poll-based API."""
        deadline = time.monotonic() + timeout
        while True:
            raw = self.get_raw(name, namespace)
            for cond in (raw.get("status") or {}).get("conditions") or []:
                if cond.get("type") == condition_type and cond.get("status") == "True":
                    return cond
            if time.monotonic() >= deadline:
                raise TimeoutError(
                    f"jobset {namespace}/{name} never reached condition {condition_type}"
                )
            time.sleep(poll)

    # -- core resources ---------------------------------------------------

    def pods(self, namespace: str = "default") -> list[dict]:
        return self._request("GET", f"/api/v1/namespaces/{namespace}/pods")["items"]

    def jobs(self, namespace: str = "default") -> list[dict]:
        return self._request("GET", f"/api/v1/namespaces/{namespace}/jobs")["items"]

    def services(self, namespace: str = "default") -> list[dict]:
        return self._request("GET", f"/api/v1/namespaces/{namespace}/services")["items"]

    def events(self) -> list[dict]:
        return self._request("GET", "/api/v1/events")["items"]

    def nodes(self) -> list[dict]:
        return self._request("GET", "/api/v1/nodes")["items"]

    def create_node(self, name: str, labels: Optional[dict] = None,
                    capacity: int = 110, taints: Optional[list[dict]] = None) -> dict:
        body = json.dumps({
            "metadata": {"name": name, "labels": labels or {}},
            "spec": {"taints": taints or []},
            "status": {"capacity": capacity},
        }).encode()
        return self._request("POST", "/api/v1/nodes", body)

    def patch_node(self, name: str, labels: Optional[dict] = None,
                   taints: Optional[list[dict]] = None) -> dict:
        patch: dict = {}
        if labels is not None:
            patch.setdefault("metadata", {})["labels"] = labels
        if taints is not None:
            patch.setdefault("spec", {})["taints"] = taints
        return self._request("PATCH", f"/api/v1/nodes/{name}", json.dumps(patch).encode())

    # -- infra ------------------------------------------------------------

    def healthz(self) -> bool:
        try:
            return self._request("GET", "/healthz") == "ok"
        except (ApiError, urllib.error.URLError):
            return False

    def readyz(self) -> bool:
        try:
            return self._request("GET", "/readyz") == "ok"
        except (ApiError, urllib.error.URLError):
            return False

    def metrics_text(self) -> str:
        return self._request("GET", "/metrics")
