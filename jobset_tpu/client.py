"""Typed Python client for the controller server.

Analog of the reference's generated clients (`client-go/` typed clientset
and the OpenAPI Python SDK, `sdk/python/README.md:1-10`) — but hand-written
against the controller's REST surface, returning the same `JobSet` dataclass
types the rest of the framework uses instead of a parallel generated model
hierarchy.  stdlib-only (urllib), so user containers need no extra deps to
talk to the control plane.
"""

from __future__ import annotations

import json
import random
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
from typing import Optional

from . import wire
from .api import serialization
from .api.types import JobSet
from .obs import trace as obs_trace


class _KeepAlivePool:
    """Persistent keep-alive HTTP transport: one `http.client` connection
    per (pool, thread), reused across requests so the hot API path stops
    paying a TCP (and TLS) setup per call (docs/protocol.md "Connection
    discipline"). Thread-local by construction — informer threads, the
    retry loop and user threads each ride their own socket, so no
    cross-thread request interleaving is possible.

    Stale-connection discipline: a server may close an idle keep-alive
    connection at any time. A failure on a REUSED connection is retried
    exactly once on a fresh connection ONLY when re-sending is safe: the
    method is idempotent (GET/HEAD), or the request provably never went
    out (CannotSendRequest). A mutation whose reused connection dies
    after the send is ambiguous — the server may have processed it — so
    it propagates as URLError and the caller keeps owning that
    ambiguity, exactly as with the old per-request transport (mutations
    are never auto-retried anywhere in this client). A response that
    fails AFTER its status line arrived is never retried for any method
    — the request was definitively processed."""

    def __init__(self, base_url: str, timeout: float, ssl_context=None):
        from urllib.parse import urlsplit

        parts = urlsplit(base_url)
        self.scheme = parts.scheme
        self.host = parts.hostname or "127.0.0.1"
        self.port = parts.port
        self.timeout = timeout
        self._ssl_context = ssl_context
        self._local = threading.local()

    def _connect(self):
        import http.client

        if self.scheme == "https":
            return http.client.HTTPSConnection(
                self.host, self.port, timeout=self.timeout,
                context=self._ssl_context,
            )
        return http.client.HTTPConnection(
            self.host, self.port, timeout=self.timeout
        )

    def close(self) -> None:
        conn = getattr(self._local, "conn", None)
        if conn is not None:
            try:
                conn.close()
            except OSError:
                pass
            self._local.conn = None

    def request(self, method: str, path: str, body, headers: dict,
                timeout: Optional[float] = None):
        """One round trip -> (status, response headers, body bytes).
        Transport-level failures raise urllib.error.URLError (matching
        what the urlopen path raised, so retry classification upstream
        is unchanged)."""
        import http.client

        effective_timeout = self.timeout if timeout is None else timeout
        for attempt in (0, 1):
            conn = getattr(self._local, "conn", None)
            reused = conn is not None
            if conn is None:
                conn = self._connect()
                self._local.conn = conn
            got_response = False
            try:
                # Per-request deadline, restored EVERY call: a previous
                # watch long-poll's longer deadline must not leak onto
                # this thread's later ordinary requests.
                if conn.sock is not None:
                    conn.sock.settimeout(effective_timeout)
                conn.request(method, path, body=body, headers=headers)
                if conn.sock is not None:
                    conn.sock.settimeout(effective_timeout)
                resp = conn.getresponse()
                got_response = True
                data = resp.read()
            except (http.client.RemoteDisconnected,
                    http.client.CannotSendRequest,
                    BrokenPipeError, ConnectionResetError) as exc:
                self.close()
                # One redo on a stale idle keep-alive connection — but
                # ONLY when re-sending cannot double-apply: idempotent
                # methods, or a request that never left the client. A
                # mutation that failed after send is ambiguous (the
                # server may have committed it before the connection
                # died) and must surface, not silently re-send. A
                # failure after the status line arrived is never
                # retried: the request was definitively processed.
                safe_redo = (
                    method in ("GET", "HEAD")
                    or isinstance(exc, http.client.CannotSendRequest)
                )
                if reused and attempt == 0 and safe_redo and \
                        not got_response:
                    continue
                raise urllib.error.URLError(exc) from None
            except (http.client.HTTPException, OSError) as exc:
                self.close()
                raise urllib.error.URLError(exc) from None
            if resp.will_close:
                self.close()
            return resp.status, resp.headers, data


class ApiError(Exception):
    def __init__(self, status: int, message: str,
                 retry_after: Optional[float] = None,
                 leader_address: Optional[str] = None,
                 shard: Optional[int] = None):
        super().__init__(f"HTTP {status}: {message}")
        self.status = status
        self.message = message
        # Server-provided Retry-After hint in seconds (None when absent):
        # the flow-control plane's 429 sheds and every 503 write fence
        # carry one so clients back off at the server's pace instead of
        # guessing.
        self.retry_after = retry_after
        # Leader hint from a standby/follower 503 fence or a shard
        # member's 421 misroute: the FULL advertised route
        # (scheme://host:port) of whoever can actually serve this key —
        # safe GETs follow it one hop (docs/sharding.md), callers of
        # mutations decide for themselves.
        self.leader_address = leader_address
        self.shard = shard


# Statuses a GET may safely retry: the request was never processed (503
# standby/overload, 502/504 proxy hops, 429 throttles) or failed opaquely
# server-side (500). Mutations are NOT retried — an apiserver 500 may have
# landed the write, and the caller owns that ambiguity.
_RETRYABLE_STATUSES = frozenset({429, 500, 502, 503, 504})

# Statuses whose Retry-After hint is authoritative pacing (flow-control
# sheds and write fences); other retryables keep the jittered backoff.
_HINTED_STATUSES = frozenset({429, 503})

# Ceiling on an honored Retry-After hint — the same bound the informer
# watch-retry backoff already uses, so a confused server cannot park a
# client arbitrarily long.
RETRY_AFTER_CAP_S = 5.0


def _parse_retry_after(value) -> Optional[float]:
    """Retry-After header -> seconds. Only the delta-seconds form is
    understood (our servers emit nothing else); anything unparsable OR
    non-positive is treated as absent — honoring a zero hint as
    "retry immediately" would turn the retry loop into a hot hammer on
    a server that is actively shedding, so those fall back to the
    jittered backoff."""
    if not value:
        return None
    try:
        seconds = float(value)
    except (TypeError, ValueError):
        return None
    return seconds if seconds > 0 else None


class JobSetClient:
    """Client bound to one controller server (`http://host:port`).

    Idempotent requests (GETs: reads, lists, health probes) ride through
    transient server trouble with `retries` attempts of exponential
    backoff + full jitter (the AWS-architecture-blog discipline: sleep
    U(0, min(cap, base * 2^attempt)) so a thundering herd of recovering
    clients decorrelates). Mutations are never retried here.
    """

    API = "/apis/jobset.x-k8s.io/v1alpha2"

    def __init__(
        self,
        base_url: str,
        timeout: float = 30.0,
        ca_cert: Optional[str] = None,
        retries: int = 4,
        backoff_base_s: float = 0.05,
        backoff_cap_s: float = 2.0,
        retry_seed: Optional[int] = None,
        user_agent: Optional[str] = None,
        chaos_src: str = "client",
        encoding: str = "json",
    ):
        """ca_cert: path to the PEM CA that signed the controller's serving
        cert (utils/certs.py writes it as ca.crt) — enables https:// URLs
        with verification against the self-signed chain.
        retries: extra attempts for idempotent (GET) requests on 429/5xx
        and transport errors; retry_seed makes the jitter reproducible.
        user_agent: sent on every request — the flow-control plane's flow
        distinguisher, so name your tenant/controller here for fair
        shuffle-sharding (default: jobset-tpu-client/<version>).
        chaos_src: this client's identity on the network fault model's
        directed links (chaos/net.py): every HTTP round trip is one
        delivery over (chaos_src, server netloc) — a PartitionPlan that
        cuts the link makes requests fail like a blackholed network
        (URLError), engaging the same GET-retry/informer-backoff paths a
        real partition would.
        encoding: "json" (default — wire-compatible with every server) or
        "binary" (docs/protocol.md): structured request bodies ship as
        application/vnd.jobset.binary frames and responses are requested
        in the same encoding via Accept. Mixed versions interoperate: a
        server that never learned the media type ignores the Accept and
        answers JSON, which this client always still parses."""
        from . import __version__

        if encoding not in ("json", "binary"):
            raise ValueError(
                f"unknown client encoding {encoding!r} "
                "(expected 'json' or 'binary')"
            )
        self.encoding = encoding
        if "://" not in base_url:
            base_url = f"{'https' if ca_cert else 'http'}://{base_url}"
        self.base_url = base_url.rstrip("/")
        self.timeout = timeout
        self.retries = max(0, retries)
        self.backoff_base_s = backoff_base_s
        self.backoff_cap_s = backoff_cap_s
        self._retry_rng = random.Random(retry_seed)
        self.retried_requests = 0
        self.user_agent = user_agent or f"jobset-tpu-client/{__version__}"
        from urllib.parse import urlsplit

        self.chaos_src = chaos_src
        # The directed-link destination for the network fault model: the
        # server's netloc, matching what a PartitionPlan cut names.
        self._chaos_dst = urlsplit(self.base_url).netloc
        # Pacing hint from the last successful watch poll (the flow
        # plane's saturated-watch-pool partial batches carry one); the
        # informer consults it between polls.
        self.last_watch_retry_after: Optional[float] = None
        self._ssl_context = None
        if ca_cert is not None:
            import ssl

            self._ssl_context = ssl.create_default_context(cafile=ca_cert)
            # The self-signed serving cert names localhost/127.0.0.1; tests
            # and compose deployments connect by those, so hostname checking
            # stays ON (the SANs cover it).
        # Persistent keep-alive transport (docs/protocol.md "Connection
        # discipline"): every request — reads, writes, watch long-polls —
        # reuses one thread-local connection instead of a fresh TCP(+TLS)
        # setup per call.
        self._pool = _KeepAlivePool(
            self.base_url, timeout, ssl_context=self._ssl_context
        )

    def close(self) -> None:
        """Close this thread's pooled keep-alive connection (other
        threads' connections close when their threads exit)."""
        self._pool.close()

    # -- transport --------------------------------------------------------

    def _request(self, method: str, path: str, body: Optional[bytes] = None,
                 content_type: str = "application/json"):
        headers = {"Content-Type": content_type} if body is not None else {}
        headers["User-Agent"] = self.user_agent
        # Client span + W3C traceparent injection: the server extracts the
        # header and parents its apiserver.request span on this one, so a
        # single trace covers client -> apiserver -> reconcile -> solver.
        # Standalone GETs (health probes, wait_for_condition polls) are
        # traced only when they run under an existing span — a poll loop
        # must not churn the trace ring with one-span root traces.
        if method == "GET" and obs_trace.current_span() is None:
            return self._transport(method, path, body, headers)[0]
        with obs_trace.span(
            "client.request", {"http.method": method, "http.path": path}
        ) as client_span:
            headers["traceparent"] = client_span.context.to_traceparent()
            try:
                out, status = self._transport(method, path, body, headers)
            except ApiError as exc:
                client_span.set_attribute("http.status", exc.status)
                raise
            client_span.set_attribute("http.status", status)
            return out

    def _backoff_sleep(self, attempt: int) -> None:
        """Full-jitter exponential backoff: U(0, min(cap, base * 2^n))."""
        cap = min(self.backoff_cap_s, self.backoff_base_s * (2 ** attempt))
        time.sleep(self._retry_rng.uniform(0.0, cap))

    def _transport(self, method: str, path: str, body, headers):
        """One logical HTTP round trip; returns (payload, status).

        GETs retry `self.retries` times on retryable statuses and raw
        transport errors (connection refused/reset — the server may be
        mid-restart) with exponential backoff + full jitter; every other
        method gets exactly one attempt. A 429/503 carrying a server
        Retry-After hint is honored (capped at RETRY_AFTER_CAP_S) instead
        of the jittered guess — the server knows its own queue pressure."""
        attempts = 1 + (self.retries if method == "GET" else 0)
        followed_hint = False
        for attempt in range(attempts):
            hint = None
            try:
                return self._transport_once(method, path, body, headers)
            except ApiError as exc:
                # One-hop leader-hint redirect for safe GETs: a standby/
                # follower fence 503 (or a shard 421) carrying the full
                # advertised route is answered by asking THAT server
                # directly, once — beats waiting out Retry-After rounds
                # against a replica that told us who can serve. A failed
                # hop falls back to the ordinary retry loop.
                if (
                    method == "GET"
                    and not followed_hint
                    and exc.status in self._HINT_FOLLOW_STATUSES
                    and exc.leader_address
                ):
                    followed_hint = True
                    try:
                        return self._follow_leader_hint(
                            method, path, headers, exc.leader_address
                        )
                    # ValueError: a malformed advertised route (urlsplit
                    # port parse) — a bad hint must degrade to the
                    # ordinary retry loop, never crash the GET.
                    except (ApiError, urllib.error.URLError, OSError,
                            ValueError):
                        pass
                if (
                    attempt + 1 >= attempts
                    or exc.status not in _RETRYABLE_STATUSES
                ):
                    raise
                if exc.status in _HINTED_STATUSES:
                    hint = exc.retry_after
            except urllib.error.URLError:
                if attempt + 1 >= attempts:
                    raise
            self.retried_requests += 1
            if hint is not None:
                time.sleep(min(hint, RETRY_AFTER_CAP_S))
            else:
                self._backoff_sleep(attempt)

    def _check_link(self) -> None:
        """One delivery over the (chaos_src, server) link of the network
        fault model: raises URLError while the active PartitionPlan has
        the link cut (or a `net.partition` rate rule fires), so a cut
        behaves exactly like a blackholed network — GET retries and
        informer backoff engage, mutations fail to the caller."""
        from .chaos import net as chaos_net

        reason = chaos_net.check_link(self.chaos_src, self._chaos_dst)
        if reason is not None:
            raise urllib.error.URLError(reason)

    @staticmethod
    def _parse_payload(data: bytes, ctype: str):
        """Response bytes -> Python payload by Content-Type (binary wire
        frames, JSON, or plain text — whatever the server negotiated)."""
        if ctype.startswith(wire.CONTENT_TYPE):
            return wire.decode(data)
        if ctype.startswith("application/json"):
            return json.loads(data)
        return data.decode()

    @staticmethod
    def _error_detail(data: bytes):
        detail = data.decode(errors="replace")
        try:
            detail = json.loads(detail).get("error", detail)
        except (json.JSONDecodeError, AttributeError):
            pass
        return detail

    @staticmethod
    def _error_fields(data: bytes):
        """(detail, leaderAddress, shard) from an error body: fence 503s
        and shard 421s carry a followable full-route leader hint."""
        detail = data.decode(errors="replace")
        leader = shard = None
        try:
            doc = json.loads(detail)
            detail = doc.get("error", detail)
            leader = doc.get("leaderAddress") or None
            shard = doc.get("shard")
        except (json.JSONDecodeError, AttributeError):
            pass
        return detail, leader, shard

    def _transport_once(self, method: str, path: str, body, headers):
        """One HTTP round trip over the keep-alive pool; returns
        (parsed payload, response status)."""
        self._check_link()
        if self.encoding == "binary":
            headers.setdefault("Accept", wire.CONTENT_TYPE)
        status, resp_headers, data = self._pool.request(
            method, path, body, headers
        )
        if status >= 400:
            detail, leader, shard = self._error_fields(data)
            raise ApiError(
                status, detail,
                retry_after=_parse_retry_after(
                    resp_headers.get("Retry-After")
                ),
                leader_address=leader,
                shard=shard,
            )
        return self._parse_payload(
            data, resp_headers.get("Content-Type", "")
        ), status

    # Statuses whose leader hint a safe GET follows one hop: the standby
    # /follower write-read fence (503) and a shard member's misroute
    # (421 Misdirected Request).
    _HINT_FOLLOW_STATUSES = frozenset({421, 503})

    def _follow_leader_hint(self, method: str, path: str, headers,
                            hint: str):
        """ONE-hop redirect of a safe GET to a fence/misroute response's
        advertised leader route (docs/sharding.md, docs/ha.md): a single
        direct request against the full scheme://host:port hint — no
        retries, no further hops (a second hint raises), mutations never
        ride this path (re-sending a write to a second server on a
        server-supplied hint is the caller's call, not the client's)."""
        from urllib.parse import urlsplit

        if "://" not in hint:
            hint = f"{self._pool.scheme}://{hint}"
        parts = urlsplit(hint)
        # The hop is one delivery over the (chaos_src, hinted netloc)
        # link of the network fault model, like any other round trip.
        from .chaos import net as chaos_net

        reason = chaos_net.check_link(self.chaos_src, parts.netloc)
        if reason is not None:
            raise urllib.error.URLError(reason)
        import http.client

        conn_cls = (
            http.client.HTTPSConnection if parts.scheme == "https"
            else http.client.HTTPConnection
        )
        kwargs = {"timeout": self.timeout}
        if parts.scheme == "https" and self._ssl_context is not None:
            kwargs["context"] = self._ssl_context
        conn = conn_cls(parts.hostname or "127.0.0.1", parts.port,
                        **kwargs)
        try:
            conn.request(method, path, headers=headers)
            resp = conn.getresponse()
            data = resp.read()
            if resp.status >= 400:
                detail, leader, shard = self._error_fields(data)
                raise ApiError(resp.status, detail,
                               leader_address=leader, shard=shard)
            return self._parse_payload(
                data, resp.headers.get("Content-Type", "")
            ), resp.status
        finally:
            try:
                conn.close()
            except OSError:
                pass

    # -- jobsets ----------------------------------------------------------

    def _collection(self, namespace: str) -> str:
        return f"{self.API}/namespaces/{namespace}/jobsets"

    def _encode_body(self, doc: dict) -> tuple[bytes, str]:
        """Structured request body in the client's negotiated encoding."""
        if self.encoding == "binary":
            return wire.encode(doc), wire.CONTENT_TYPE
        return json.dumps(doc).encode(), "application/json"

    @staticmethod
    def _manifest_dict(js: "JobSet | dict | str") -> dict:
        """JobSet object / manifest dict / YAML text -> manifest dict."""
        if isinstance(js, JobSet):
            return serialization.to_dict(js)
        if isinstance(js, dict):
            return js
        import yaml as _yaml

        doc = _yaml.safe_load(js)
        if not isinstance(doc, dict):
            raise ValueError("manifest text must parse to a mapping")
        return doc

    def create(self, js: JobSet | dict | str, namespace: Optional[str] = None) -> JobSet:
        """Create from a JobSet object, a manifest dict, or YAML text.

        Namespace resolution mirrors kubectl: an explicit `namespace`
        argument wins, else the manifest's own namespace, else "default".
        The server rejects a manifest whose namespace disagrees with the
        request path.
        """
        if isinstance(js, JobSet):
            manifest_ns = js.metadata.namespace
            body, ctype = self._encode_body(serialization.to_dict(js))
        elif isinstance(js, dict):
            manifest_ns = (js.get("metadata") or {}).get("namespace")
            body, ctype = self._encode_body(js)
        else:
            import yaml as _yaml

            manifest_ns = ((_yaml.safe_load(js) or {}).get("metadata") or {}).get(
                "namespace"
            )
            body, ctype = js.encode(), "application/yaml"
        ns = namespace or manifest_ns or "default"
        out = self._request("POST", self._collection(ns), body,
                            content_type=ctype)
        return serialization.from_dict(out)

    def batch_create(
        self,
        manifests,
        namespace: str = "default",
        view: str = "full",
    ) -> list[dict]:
        """One ``:batchCreate`` round trip (docs/protocol.md): every
        manifest (JobSet objects, dicts, or YAML texts) ships in a single
        request with per-item create semantics — the returned list holds
        one ``{"code": 201, "object"/"name"...}`` or
        ``{"code": 4xx, "error": ...}`` entry per input, in order; an
        invalid item never poisons its siblings. ``view="minimal"``
        returns name/uid stubs instead of full manifests (bulk loads)."""
        doc: dict = {
            "items": [self._manifest_dict(m) for m in manifests],
        }
        if view != "full":
            doc["view"] = view
        body, ctype = self._encode_body(doc)
        out = self._request(
            "POST", f"{self._collection(namespace)}:batchCreate", body,
            content_type=ctype,
        )
        return out["items"]

    def batch_update_status(
        self, items: list[dict], namespace: str = "default"
    ) -> list[dict]:
        """One ``:batchStatus`` round trip: ``items`` are
        ``{"name": ..., "status": {...}}`` wire dicts; returns the
        per-item result list (200/400/404 codes, in order)."""
        body, ctype = self._encode_body({"items": items})
        out = self._request(
            "POST", f"{self._collection(namespace)}:batchStatus", body,
            content_type=ctype,
        )
        return out["items"]

    def apply_yaml(self, text: str, namespace: Optional[str] = None) -> list[JobSet]:
        """Create every document in a (possibly multi-doc) YAML stream; each
        document's own metadata.namespace wins over the `namespace` arg."""
        import yaml as _yaml

        created = []
        for doc in _yaml.safe_load_all(text):
            if not doc:
                continue
            doc_ns = (doc.get("metadata") or {}).get("namespace")
            created.append(self.create(doc, namespace=doc_ns or namespace))
        return created

    def get(self, name: str, namespace: str = "default") -> JobSet:
        out = self._request("GET", f"{self._collection(namespace)}/{name}")
        return serialization.from_dict(out)

    def get_raw(self, name: str, namespace: str = "default") -> dict:
        """Manifest dict including status (the wire representation)."""
        return self._request("GET", f"{self._collection(namespace)}/{name}")

    def list(self, namespace: str = "default") -> list[JobSet]:
        return [serialization.from_dict(item) for item in self.list_raw(namespace)]

    def list_raw(self, namespace: str = "default") -> list[dict]:
        """Manifest dicts (status included) in one request — what the
        collection endpoint already serves; no per-item round-trips."""
        return self._request("GET", self._collection(namespace))["items"]

    def list_with_version(self, namespace: str = "default"):
        """(manifest dicts, resourceVersion) — the list half of
        list-then-watch."""
        return self.list_resource_with_version("jobsets", namespace)

    def _resource_path(self, kind: str, namespace: str) -> str:
        """Collection path for a watchable kind: jobsets live under the
        group API, child jobs/pods/services under the core API, and
        cluster events at the cluster-scoped core path."""
        if kind == "jobsets":
            return self._collection(namespace)
        if kind == "events":
            return "/api/v1/events"
        return f"/api/v1/namespaces/{namespace}/{kind}"

    def watch(self, namespace="default", resource_version=0, timeout=15.0):
        """One long-poll against the JobSet watch endpoint.

        Returns (events, resource_version): events are
        {"type": ADDED|MODIFIED|DELETED, "object": manifest,
        "resourceVersion": n}, possibly empty on timeout; the returned
        resource_version is the token for the next call. Raises WatchGone
        when the version is too old.
        """
        return self.watch_resource("jobsets", namespace, resource_version, timeout)

    @staticmethod
    def _expand_frame(frame: dict) -> list[dict]:
        """Coalesced watch frame -> the legacy per-event list
        (docs/protocol.md): rv deltas rebased on the frame's baseRV,
        PATCH events replayed against their in-frame predecessor via
        wire.apply_delta."""
        base = int(frame.get("baseRV", 0))
        events: list[dict] = []
        for entry in frame.get("events") or []:
            drv, etype = int(entry[0]), entry[1]
            if etype == "PATCH":
                obj = wire.apply_delta(
                    events[int(entry[2])]["object"], entry[3]
                )
                etype = "MODIFIED"
            else:
                obj = entry[2]
            events.append({
                "resourceVersion": base + drv,
                "type": etype,
                "object": obj,
            })
        return events

    def watch_resource(
        self, kind: str, namespace="default", resource_version=0, timeout=15.0
    ):
        """One long-poll watch for any journaled kind ("jobsets", "jobs",
        "pods", "services", "events") — the client-go generated-informer
        analog covering EVERY type an external controller consumes, so
        nothing needs polling.

        Always asks for coalesced frames (?frames=1, docs/protocol.md);
        a server that predates them ignores the parameter and answers
        the legacy per-event list, which is parsed identically — the
        mixed-version interop contract."""
        self._check_link()
        path = (
            f"{self._resource_path(kind, namespace)}?watch=1"
            f"&resourceVersion={int(resource_version)}"
            f"&timeoutSeconds={timeout}&frames=1"
        )
        headers = {"User-Agent": self.user_agent}
        if self.encoding == "binary":
            headers["Accept"] = wire.CONTENT_TYPE
        status, resp_headers, data = self._pool.request(
            "GET", path, None, headers, timeout=timeout + 10.0
        )
        if status >= 400:
            detail = self._error_detail(data)
            if status == 410:
                raise WatchGone(410, detail) from None
            raise ApiError(
                status, detail,
                retry_after=_parse_retry_after(
                    resp_headers.get("Retry-After")
                ),
            )
        out = self._parse_payload(
            data, resp_headers.get("Content-Type", "")
        )
        # Saturated-watch-pool partial batches carry a pacing hint (the
        # flow plane's thread-free long-poll mode); stash it for the
        # informer loop. None on ordinary parked polls.
        self.last_watch_retry_after = out.get("retryAfterSeconds")
        if "frame" in out:
            return self._expand_frame(out["frame"]), out["resourceVersion"]
        return out["events"], out["resourceVersion"]

    def list_resource_with_version(self, kind: str, namespace: str = "default"):
        """(manifest dicts, resourceVersion) for any journaled kind — the
        list half of list-then-watch."""
        out = self._request("GET", self._resource_path(kind, namespace))
        return out["items"], out.get("resourceVersion", 0)

    def update(self, js: JobSet, namespace: Optional[str] = None) -> JobSet:
        ns = namespace or js.metadata.namespace or "default"
        body, ctype = self._encode_body(serialization.to_dict(js))
        out = self._request("PUT", f"{self._collection(ns)}/{js.metadata.name}", body,
                            content_type=ctype)
        return serialization.from_dict(out)

    def delete(self, name: str, namespace: str = "default") -> None:
        self._request("DELETE", f"{self._collection(namespace)}/{name}")

    def update_status(self, name: str, status: dict,
                      namespace: str = "default") -> dict:
        """Write the status subresource (external controllers of managedBy
        jobsets — the k8s `/status` endpoint analog). `status` is the wire
        dict (camelCase keys); returns the stored manifest."""
        body, ctype = self._encode_body({"status": status})
        return self._request(
            "PUT", f"{self._collection(namespace)}/{name}/status", body,
            content_type=ctype,
        )

    def suspend(self, name: str, namespace: str = "default") -> JobSet:
        js = self.get(name, namespace)
        js.spec.suspend = True
        return self.update(js, namespace)

    def resume(self, name: str, namespace: str = "default") -> JobSet:
        js = self.get(name, namespace)
        js.spec.suspend = False
        return self.update(js, namespace)

    def wait_for_condition(
        self,
        name: str,
        condition_type: str,
        namespace: str = "default",
        timeout: float = 60.0,
        poll: float = 0.2,
    ) -> dict:
        """Poll until the JobSet has `condition_type` with status True;
        returns the condition dict. The watch analog for a poll-based API."""
        deadline = time.monotonic() + timeout
        while True:
            raw = self.get_raw(name, namespace)
            for cond in (raw.get("status") or {}).get("conditions") or []:
                if cond.get("type") == condition_type and cond.get("status") == "True":
                    return cond
            if time.monotonic() >= deadline:
                raise TimeoutError(
                    f"jobset {namespace}/{name} never reached condition {condition_type}"
                )
            time.sleep(poll)

    # -- core resources ---------------------------------------------------

    def pods(self, namespace: str = "default") -> list[dict]:
        return self._request("GET", f"/api/v1/namespaces/{namespace}/pods")["items"]

    def jobs(self, namespace: str = "default") -> list[dict]:
        return self._request("GET", f"/api/v1/namespaces/{namespace}/jobs")["items"]

    def services(self, namespace: str = "default") -> list[dict]:
        return self._request("GET", f"/api/v1/namespaces/{namespace}/services")["items"]

    def events(self, field_selector: Optional[str] = None) -> list[dict]:
        """Retained cluster events; `field_selector` filters server-side
        (`involvedObject.kind=JobSet,involvedObject.name=x`, plus `reason`
        and `type` — the kubectl --field-selector subset)."""
        path = "/api/v1/events"
        if field_selector:
            from urllib.parse import quote

            path += f"?fieldSelector={quote(field_selector)}"
        return self._request("GET", path)["items"]

    def events_for(self, kind: str, name: str,
                   namespace: Optional[str] = None) -> list[dict]:
        """Events whose involved object is `kind`/`name` (the kubectl
        `get events --for kind/name` analog, filtered server-side).
        `namespace` additionally scopes to the involved object's
        namespace — pass it when same-named objects may exist across
        namespaces."""
        selector = (
            f"involvedObject.kind={kind},involvedObject.name={name}"
        )
        if namespace:
            selector += f",involvedObject.namespace={namespace}"
        return self.events(field_selector=selector)

    def nodes(self) -> list[dict]:
        return self._request("GET", "/api/v1/nodes")["items"]

    def create_node(self, name: str, labels: Optional[dict] = None,
                    capacity: int = 110, taints: Optional[list[dict]] = None) -> dict:
        body = json.dumps({
            "metadata": {"name": name, "labels": labels or {}},
            "spec": {"taints": taints or []},
            "status": {"capacity": capacity},
        }).encode()
        return self._request("POST", "/api/v1/nodes", body)

    def patch_node(self, name: str, labels: Optional[dict] = None,
                   taints: Optional[list[dict]] = None) -> dict:
        patch: dict = {}
        if labels is not None:
            patch.setdefault("metadata", {})["labels"] = labels
        if taints is not None:
            patch.setdefault("spec", {})["taints"] = taints
        return self._request("PATCH", f"/api/v1/nodes/{name}", json.dumps(patch).encode())

    # -- admission queues --------------------------------------------------

    def create_queue(self, manifest: dict | str) -> dict:
        """Create an admission queue from a manifest dict or YAML text
        (kind: Queue; docs/queueing.md)."""
        if isinstance(manifest, str):
            body = manifest.encode()
        else:
            body = json.dumps(manifest).encode()
        return self._request("POST", f"{self.API}/queues", body)

    def list_queues(self) -> list[dict]:
        return self._request("GET", f"{self.API}/queues")["items"]

    def get_queue(self, name: str) -> dict:
        return self._request("GET", f"{self.API}/queues/{name}")

    def update_queue(self, name: str, manifest: dict | str) -> dict:
        if isinstance(manifest, str):
            body = manifest.encode()
        else:
            body = json.dumps(manifest).encode()
        return self._request("PUT", f"{self.API}/queues/{name}", body)

    def delete_queue(self, name: str) -> None:
        self._request("DELETE", f"{self.API}/queues/{name}")

    def queue_status(self, name: str) -> dict:
        """Quota usage + pending/admitted workload list of one queue."""
        return self._request("GET", f"{self.API}/queues/{name}/status")

    # -- infra ------------------------------------------------------------

    def healthz(self) -> bool:
        try:
            return self._request("GET", "/healthz") == "ok"
        except (ApiError, urllib.error.URLError):
            return False

    def readyz(self) -> bool:
        try:
            return self._request("GET", "/readyz") == "ok"
        except (ApiError, urllib.error.URLError):
            return False

    def metrics_text(self) -> str:
        return self._request("GET", "/metrics")

    # -- flight recorder / debug surfaces ---------------------------------

    def timeline(self, name: str, namespace: str = "default") -> dict:
        """Per-JobSet flight-recorder timeline (phases, ordered entries,
        chaos injections, store commit point; docs/observability.md)."""
        return self._request(
            "GET", f"/debug/timeline/{namespace}/{name}"
        )

    def slo_summary(self) -> dict:
        """`/debug/slo`: time-to-admission / time-to-ready / restart-
        recovery percentiles plus the solver-fallback ratio."""
        return self._request("GET", "/debug/slo")

    def health(self) -> dict:
        """`/debug/health`: the aggregated componentstatuses analog with
        an overall healthy/degraded verdict."""
        return self._request("GET", "/debug/health")

    def traces(self, limit: int = 64, phase: Optional[str] = None) -> dict:
        """`/debug/traces`: recent finished traces (limit=0 for the whole
        ring) plus the dropped-span counter. ``phase`` keeps only traces
        containing a span of that name (limit applies after the filter)."""
        path = f"/debug/traces?limit={int(limit)}"
        if phase is not None:
            path += f"&phase={urllib.parse.quote(phase)}"
        return self._request("GET", path)

    def tsdb(self, query: Optional[str] = None,
             start: Optional[float] = None, end: Optional[float] = None,
             name: Optional[str] = None) -> dict:
        """`/debug/tsdb`: with ``query``, a PromQL-lite evaluation
        (instant at the telemetry clock's now, or a stepped range when
        ``start``/``end`` are given); without, the deterministic series
        dump the debug bundle captures."""
        params = []
        if query is not None:
            params.append(f"query={urllib.parse.quote(query)}")
        if start is not None:
            params.append(f"start={start:g}")
        if end is not None:
            params.append(f"end={end:g}")
        if name is not None:
            params.append(f"name={urllib.parse.quote(name)}")
        path = "/debug/tsdb"
        if params:
            path += "?" + "&".join(params)
        return self._request("GET", path)

    def fleet_series(self, name: Optional[str] = None) -> dict:
        """`/debug/tsdb?view=fleet`: the shard front door's federated
        fleet view — every shard replica's current series merged and
        stamped with shard/replica/role labels."""
        path = "/debug/tsdb?view=fleet"
        if name is not None:
            path += f"&name={urllib.parse.quote(name)}"
        return self._request("GET", path)

    def alerts(self) -> dict:
        """`/debug/alerts`: configured alert rules, active
        pending/firing alerts, and the bounded transition log."""
        return self._request("GET", "/debug/alerts")

    def profile(self, top: Optional[int] = None):
        """`/debug/profile`: the continuous-profiling plane — sampler
        state, thread-role sample counts, hottest frames, folded stacks,
        the per-interval aggregate ring, JIT cache stats, and per-lock
        contention stats. ``top`` bounds the hottest-frames table."""
        path = "/debug/profile"
        if top is not None:
            path += f"?top={int(top)}"
        return self._request("GET", path)

    def profile_folded(self) -> str:
        """`/debug/profile?format=folded`: bare folded-stack lines —
        flamegraph.pl input."""
        return self._request("GET", "/debug/profile?format=folded")


# ---------------------------------------------------------------------------
# Watch + informer (client-go informers/listers analog,
# client-go/informers/externalversions/jobset/v1alpha2/jobset.go)
# ---------------------------------------------------------------------------


class WatchGone(ApiError):
    """The requested resourceVersion fell out of the server's journal
    window (HTTP 410): relist and restart the watch."""


class ResourceInformer:
    """Event-driven object cache with handlers and periodic resync, for any
    journaled kind ("jobsets", "jobs", "pods", "services", "events").

    The client-go shared-informer pattern over the controller's long-poll
    watch: `start()` lists (populating the cache and firing on_add), then a
    background thread watches for ADDED/MODIFIED/DELETED events, keeps
    `cache` current, and fires the handlers. A 410 from the server (journal
    window passed) and the `resync_seconds` cadence both trigger a relist
    that reconciles the cache (firing synthetic add/update/delete for any
    drift), so handlers converge even across missed events.
    """

    KIND = "jobsets"

    def __init__(
        self,
        client: JobSetClient,
        namespace: str = "default",
        resync_seconds: float = 30.0,
        on_add=None,
        on_update=None,
        on_delete=None,
        poll_timeout: float = 5.0,
        kind: Optional[str] = None,
    ):
        self.client = client
        self.kind = kind or self.KIND
        self.namespace = namespace
        self.resync_seconds = resync_seconds
        self.poll_timeout = poll_timeout
        self.on_add = on_add
        self.on_update = on_update
        self.on_delete = on_delete
        self.cache: dict[str, dict] = {}
        self._rv = 0
        self._stop = threading.Event()
        self._thread = None
        self._synced = threading.Event()

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "ResourceInformer":
        self._relist()
        self._synced.set()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.poll_timeout + 15.0)

    def has_synced(self) -> bool:
        return self._synced.is_set()

    # -- internals ---------------------------------------------------------

    @staticmethod
    def _name(obj: dict) -> str:
        return (obj.get("metadata") or {}).get("name", "")

    def _fire(self, handler, *args) -> None:
        if handler is None:
            return
        try:
            handler(*args)
        except Exception:  # a broken handler must not kill the watch loop
            import logging

            logging.getLogger("jobset_tpu.client").exception(
                "informer handler failed"
            )

    # Whether a relist reconciles deletions (fires on_delete and evicts
    # cached objects absent from the list). True for real objects, where
    # absence means deletion; False for append-only record streams
    # (events), where absence only means server retention trimmed them —
    # the watcher owns its own retention.
    RELIST_DELETES = True

    def _relist(self) -> None:
        items, rv = self.client.list_resource_with_version(
            self.kind, self.namespace
        )
        fresh = {self._name(obj): obj for obj in items}
        for name, obj in fresh.items():
            if name not in self.cache:
                self._fire(self.on_add, obj)
            elif self.cache[name] != obj:
                self._fire(self.on_update, self.cache[name], obj)
        if self.RELIST_DELETES:
            for name, obj in list(self.cache.items()):
                if name not in fresh:
                    self._fire(self.on_delete, obj)
            self.cache = fresh
        else:
            self.cache.update(fresh)
        self._rv = rv

    def _apply(self, event: dict) -> None:
        obj = event["object"]
        name = self._name(obj)
        etype = event["type"]
        if etype == "ADDED":
            self.cache[name] = obj
            self._fire(self.on_add, obj)
        elif etype == "MODIFIED":
            old = self.cache.get(name)
            self.cache[name] = obj
            self._fire(self.on_update, old, obj)
        elif etype == "DELETED":
            self.cache.pop(name, None)
            self._fire(self.on_delete, obj)

    # Watch-retry backoff bounds: persistent errors (controller down for
    # minutes) must neither tight-loop the thread nor grow the sleep
    # unboundedly — exponential from MIN, capped at MAX, reset by the
    # first successful poll.
    WATCH_BACKOFF_MIN_S = 0.2
    WATCH_BACKOFF_MAX_S = 5.0

    def _run(self) -> None:
        import time as _t

        next_resync = _t.monotonic() + self.resync_seconds
        backoff = self.WATCH_BACKOFF_MIN_S
        while not self._stop.is_set():
            try:
                events, rv = self.client.watch_resource(
                    self.kind, self.namespace, self._rv,
                    timeout=self.poll_timeout,
                )
                for event in events:
                    self._apply(event)
                self._rv = rv
                backoff = self.WATCH_BACKOFF_MIN_S  # healthy again
                # Saturated watch pool: the server answered immediately
                # (partial batch + hint) instead of parking the poll —
                # honor the pacing hint (bounded) so re-polls don't spin.
                hint = getattr(self.client, "last_watch_retry_after", None)
                if hint:
                    if self._stop.wait(
                        min(float(hint), self.WATCH_BACKOFF_MAX_S)
                    ):
                        return
            except WatchGone:
                try:
                    self._relist()
                    backoff = self.WATCH_BACKOFF_MIN_S
                except Exception:
                    # The catch-up list itself failed (controller restart
                    # mid-410?): back off and retry — the loop must never
                    # die silently with a stale cache.
                    if self._stop.wait(backoff):
                        return
                    backoff = min(backoff * 2, self.WATCH_BACKOFF_MAX_S)
            except ApiError as exc:
                # Throttled (429 shed) or fenced (503): a server hint is
                # authoritative pacing, capped at the same ceiling the
                # exponential path respects; without one, back off as for
                # any transport error. Either way resume with the SAME
                # resourceVersion — the journal holds the gap.
                hint = (
                    exc.retry_after
                    if exc.status in _HINTED_STATUSES else None
                )
                if self._stop.wait(
                    min(hint, self.WATCH_BACKOFF_MAX_S)
                    if hint is not None else backoff
                ):
                    return
                if hint is None:
                    backoff = min(backoff * 2, self.WATCH_BACKOFF_MAX_S)
            except Exception:
                # Transient transport error: back off (bounded, growing)
                # then resume with the SAME resourceVersion — the journal
                # still holds anything missed inside the gap.
                if self._stop.wait(backoff):
                    return
                backoff = min(backoff * 2, self.WATCH_BACKOFF_MAX_S)
            if _t.monotonic() >= next_resync:
                try:
                    self._relist()
                except Exception:
                    pass
                next_resync = _t.monotonic() + self.resync_seconds


class JobSetInformer(ResourceInformer):
    """JobSet informer (back-compat name; client-go jobset informer analog)."""

    KIND = "jobsets"


class JobInformer(ResourceInformer):
    """Child-Job informer (client-go batch/v1 Job informer analog)."""

    KIND = "jobs"


class PodInformer(ResourceInformer):
    """Pod informer (client-go core/v1 Pod informer analog)."""

    KIND = "pods"


class ServiceInformer(ResourceInformer):
    """Headless-Service informer (client-go core/v1 Service informer
    analog): watches the per-JobSet subdomain services the reconciler
    materializes for DNS rendezvous."""

    KIND = "services"


class EventInformer(ResourceInformer):
    """Cluster-event informer (client-go core/v1 Event informer analog).
    Events are append-only records streamed by cursor (never MODIFIED;
    no DELETED on retention trim), cached under their `evt-{seq}` name.
    Relists never fire on_delete (RELIST_DELETES=False): an event absent
    from a fresh list was trimmed by server retention, not deleted —
    cache retention is this watcher's own concern."""

    KIND = "events"
    RELIST_DELETES = False
