"""API priority & fairness analog for the controller server (docs/flow.md).

``config`` declares priority levels, flow schemas and the DRF004-checked
route classification table; ``controller`` implements seat accounting,
shuffle-sharded bounded queueing, and 429-shedding. Enabled by the
``APIFlowControl`` feature gate (or an explicit ``FlowController`` passed
to ``ControllerServer``).
"""

from .config import (
    DEFAULT_LEVELS,
    DEFAULT_SCHEMAS,
    HIGH_PRIORITY_THRESHOLD,
    ROUTE_CLASSES,
    FlowSchema,
    PriorityLevel,
    RequestInfo,
    classify,
    request_info,
    route_class,
)
from .controller import (
    BUSY,
    EXECUTE,
    QUEUED,
    REASON_QUEUE_FULL,
    REASON_SATURATED,
    REASON_TIMEOUT,
    REASON_WATCH_BUSY,
    REJECT,
    FlowController,
    FlowTicket,
)

__all__ = [
    "BUSY",
    "DEFAULT_LEVELS",
    "DEFAULT_SCHEMAS",
    "EXECUTE",
    "FlowController",
    "FlowSchema",
    "FlowTicket",
    "HIGH_PRIORITY_THRESHOLD",
    "PriorityLevel",
    "QUEUED",
    "REASON_QUEUE_FULL",
    "REASON_SATURATED",
    "REASON_TIMEOUT",
    "REASON_WATCH_BUSY",
    "REJECT",
    "ROUTE_CLASSES",
    "RequestInfo",
    "classify",
    "request_info",
    "route_class",
]
