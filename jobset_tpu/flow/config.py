"""Flow-control configuration: priority levels, flow schemas, and the
route classification table.

The kube-apiserver survives request storms with API Priority & Fairness
(KEP-1040): requests are matched by *FlowSchemas* into *PriorityLevels*,
each with a bounded concurrency budget ("seats") and bounded queues that
shuffle-shard flows so one noisy tenant cannot occupy every queue. This
module is the declarative half of our analog:

* :class:`PriorityLevel` — seats, queue geometry, queue-wait budget and
  the ``Retry-After`` hint sheds carry.
* :class:`FlowSchema` — matching rules over the request descriptor
  (verb, resource kind, namespace, user-agent prefix, JobSet
  ``spec.priority``); first match wins, ordered.
* ``ROUTE_CLASSES`` — the exempt/classified partition of every HTTP
  route the controller server registers. Lint rule **DRF004**
  (docs/static-analysis.md) machine-checks this table against
  ``server.py``'s route literals in both directions: an unclassified
  route and a stale classification row both fail the tier-1 gate.

The runtime half (seat accounting, queueing, shedding) lives in
:mod:`jobset_tpu.flow.controller`.
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Optional
from urllib.parse import parse_qs

# ---------------------------------------------------------------------------
# Route classification (the DRF004 contract)
# ---------------------------------------------------------------------------

# Every HTTP route served by `ControllerServer` maps to a route class.
# A pattern ending in "/" matches as a prefix; otherwise it matches the
# exact path or any subpath below it (`P` covers `P` and `P/...`).
#
# Classes:
#   "exempt"       — never queued, never shed: observability (/debug/*,
#                    probes, /metrics), replication internals (/ha/*) and
#                    lease/leader traffic must keep working while user
#                    traffic sheds, or the instruments that prove recovery
#                    go blind exactly when they matter.
#   "system"       — control-plane-to-control-plane traffic (admission
#                    webhook reviews): bounded, generously queued.
#   "workload"     — user API traffic; refined into workload-high /
#                    workload-low by the FlowSchemas below.
#   "workload-low" — fixed-low routes (schema discovery).
ROUTE_CLASSES: tuple[tuple[str, str], ...] = (
    ("/healthz", "exempt"),
    ("/readyz", "exempt"),
    ("/leaderz", "exempt"),
    ("/metrics", "exempt"),
    ("/debug/", "exempt"),
    ("/ha/", "exempt"),
    ("/openapi/v2", "workload-low"),
    ("/validate-jobset-x-k8s-io-v1alpha2-jobset", "system"),
    ("/mutate-jobset-x-k8s-io-v1alpha2-jobset", "system"),
    ("/apis/jobset.x-k8s.io/v1alpha2", "workload"),
    ("/api/v1", "workload"),
)

# JobSet spec.priority at or above this classifies the write as
# workload-high (the Tesserae-style mixed-priority tenant split).
HIGH_PRIORITY_THRESHOLD = 100


def pattern_covers(pattern: str, path: str) -> bool:
    """Whether a ROUTE_CLASSES pattern matches a path (shared with the
    DRF004 lint so the runtime and the check cannot drift)."""
    if path == pattern:
        return True
    prefix = pattern if pattern.endswith("/") else pattern + "/"
    return path.startswith(prefix)


def route_class(bare_path: str) -> str:
    """Longest-match classification of a bare (query-stripped) path.
    Unknown paths (404s) fall through to "workload" so junk traffic is
    subject to the same fairness budget as real user traffic."""
    best_pattern, best_class = "", "workload"
    for pattern, cls in ROUTE_CLASSES:
        if pattern_covers(pattern, bare_path) and len(pattern) > len(
            best_pattern
        ):
            best_pattern, best_class = pattern, cls
    return best_class


# ---------------------------------------------------------------------------
# Priority levels
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class PriorityLevel:
    """One bounded concurrency class (the APF PriorityLevelConfiguration
    analog).

    ``seats``: concurrent executing requests; <= 0 means unlimited (the
    exempt class). ``queues``/``queue_length``: shuffle-sharded bounded
    FIFO parking for arrivals past the seats; 0 queues means saturation
    sheds (or, for watch long-polls, answers an immediate partial batch).
    ``queue_wait_s``: how long a parked request may wait for a seat
    before it is shed with 429. ``retry_after_s``: the Retry-After hint
    stamped on sheds and watch-busy hints. ``hand_size``: how many
    candidate queues one flow shuffle-shards across."""

    name: str
    seats: int
    queues: int = 0
    queue_length: int = 0
    queue_wait_s: float = 0.0
    retry_after_s: float = 1.0
    hand_size: int = 2


# Level names used by the default config (and the health/metrics labels).
LEVEL_EXEMPT = "exempt"
LEVEL_SYSTEM = "system"
LEVEL_HIGH = "workload-high"
LEVEL_LOW = "workload-low"
LEVEL_WATCH = "watch"

DEFAULT_LEVELS: tuple[PriorityLevel, ...] = (
    PriorityLevel(LEVEL_EXEMPT, seats=0),
    PriorityLevel(LEVEL_SYSTEM, seats=16, queues=2, queue_length=32,
                  queue_wait_s=5.0),
    PriorityLevel(LEVEL_HIGH, seats=16, queues=8, queue_length=16,
                  queue_wait_s=2.0),
    PriorityLevel(LEVEL_LOW, seats=16, queues=8, queue_length=16,
                  queue_wait_s=1.0, retry_after_s=2.0),
    # Long-poll watches get their own seat pool so parked polls cannot
    # exhaust the handler threads user writes need; past the pool a
    # watch is answered immediately with a partial batch + retry hint
    # instead of parking (never 429 — watches are reads).
    PriorityLevel(LEVEL_WATCH, seats=32),
)


# ---------------------------------------------------------------------------
# Flow schemas
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class FlowSchema:
    """One matching rule routing requests of the "workload" route class
    into a priority level (the APF FlowSchema analog). Empty tuples
    match anything; ``min_priority`` matches JobSet writes whose peeked
    ``spec.priority`` is at least the bound."""

    name: str
    level: str
    verbs: tuple[str, ...] = ()
    kinds: tuple[str, ...] = ()
    namespaces: tuple[str, ...] = ()
    user_agent_prefixes: tuple[str, ...] = ()
    min_priority: Optional[int] = None

    def matches(self, info: "RequestInfo") -> bool:
        if self.verbs and info.verb not in self.verbs:
            return False
        if self.kinds and info.kind not in self.kinds:
            return False
        if self.namespaces and info.namespace not in self.namespaces:
            return False
        if self.user_agent_prefixes and not any(
            info.user_agent.startswith(p) for p in self.user_agent_prefixes
        ):
            return False
        if self.min_priority is not None and (
            info.priority is None or info.priority < self.min_priority
        ):
            return False
        return True


DEFAULT_SCHEMAS: tuple[FlowSchema, ...] = (
    # Batched verbs (:batchCreate/:batchStatus, docs/protocol.md) get
    # their own schemas so their seat accounting is explicit: one batch
    # request occupies `items` seats of its level (width accounting in
    # flow/controller.py), keeping a 64-item batch as expensive to the
    # fairness budget as 64 single writes. The priority SPLIT is
    # inherited, not escalated — a batch rides workload-high only when
    # its peeked max item priority clears the same bar a single write
    # would need, else it lands in workload-low with every other
    # best-effort write. Batching buys round trips, never priority.
    FlowSchema("batch-high-priority-gangs", level=LEVEL_HIGH,
               verbs=("batch",), min_priority=HIGH_PRIORITY_THRESHOLD),
    FlowSchema("batch-verbs", level=LEVEL_LOW, verbs=("batch",)),
    # High-priority gang writes ride the protected level: a priority>=100
    # JobSet create/update must land even while best-effort traffic sheds.
    FlowSchema("high-priority-gangs", level=LEVEL_HIGH, kinds=("jobsets",),
               min_priority=HIGH_PRIORITY_THRESHOLD),
    # Cluster operations (queue quota admin, node lifecycle) are operator
    # traffic, not tenant traffic.
    FlowSchema("cluster-ops", level=LEVEL_HIGH, kinds=("queues", "nodes")),
    FlowSchema("catch-all", level=LEVEL_LOW),
)


# ---------------------------------------------------------------------------
# Request descriptor
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class RequestInfo:
    """Everything the classifier sees about one arrival."""

    method: str
    path: str  # bare (query-stripped)
    verb: str  # create/update/delete/patch/get/watch/batch
    kind: str  # jobsets/queues/nodes/pods/jobs/services/events/webhooks/""
    namespace: str
    user_agent: str
    priority: Optional[int] = None
    is_watch: bool = False
    # Seat width: 1 for ordinary requests; a batched verb carries its
    # item count so flow admission charges `items` seats for the one
    # request (per-item seat accounting, docs/protocol.md).
    items: int = 1

    @property
    def flow_key(self) -> str:
        """The flow distinguisher: (client identity, namespace) — one
        tenant's storm shuffle-shards away from another's."""
        return f"{self.user_agent}|{self.namespace}"


# Cheap spec.priority peek over the first bytes of a JobSet manifest —
# works for both JSON (`"priority": 100`) and YAML (`priority: 100`)
# without paying a full parse on a request that may be shed anyway (the
# admission chain re-parses authoritatively after admission).
_PRIORITY_RE = re.compile(rb'[\'"]?priority[\'"]?\s*:\s*(-?\d+)')
_PEEK_BYTES = 4096

_GROUP_PREFIX = "/apis/jobset.x-k8s.io/v1alpha2"


def _peek_priority(body: bytes) -> Optional[int]:
    m = _PRIORITY_RE.search(body[:_PEEK_BYTES])
    return int(m.group(1)) if m else None


def _resource_kind(bare: str) -> str:
    parts = [p for p in bare.split("/") if p]
    if bare.startswith(_GROUP_PREFIX):
        if len(parts) >= 4 and parts[3] == "queues":
            return "queues"
        return "jobsets"
    if parts[:2] == ["api", "v1"] and len(parts) >= 3:
        if parts[2] == "namespaces" and len(parts) >= 5:
            return parts[4]
        return parts[2]  # nodes, events
    if bare.startswith("/validate-") or bare.startswith("/mutate-"):
        return "webhooks"
    return ""


def _namespace_of(bare: str) -> str:
    parts = [p for p in bare.split("/") if p]
    try:
        i = parts.index("namespaces")
    except ValueError:
        return ""
    return parts[i + 1] if i + 1 < len(parts) else ""


_VERBS = {"POST": "create", "PUT": "update", "DELETE": "delete",
          "PATCH": "patch"}


def _is_batch(bare: str, method: str) -> bool:
    from ..wire import BATCH_SUFFIXES

    return method == "POST" and bare.endswith(BATCH_SUFFIXES)


def request_info(method: str, path: str, body: bytes = b"",
                 headers: Optional[dict] = None,
                 body_obj=None) -> RequestInfo:
    """Build the classifier's request descriptor from the raw request.

    ``body_obj``: the already-decoded body document when the server
    negotiated a binary request encoding (or pre-parsed a batch body for
    width accounting) — priority/item peeks read it directly instead of
    regex-scanning bytes that are no longer JSON/YAML text."""
    bare, _, query = path.partition("?")
    is_watch = bool(parse_qs(query).get("watch"))
    kind = _resource_kind(bare)
    priority = None
    items = 1
    if _is_batch(bare, method) and isinstance(body_obj, dict):
        batch_items = body_obj.get("items")
        if isinstance(batch_items, list):
            items = max(1, len(batch_items))
            # Batch priority = max item priority: the whole batch rides
            # the level its most protected item would have earned alone.
            peeked = [
                (item.get("spec") or {}).get("priority")
                for item in batch_items
                if isinstance(item, dict)
            ]
            peeked = [p for p in peeked if isinstance(p, int)]
            if peeked:
                priority = max(peeked)
    elif kind == "jobsets" and method in ("POST", "PUT"):
        if isinstance(body_obj, dict):
            priority = (body_obj.get("spec") or {}).get("priority")
            if not isinstance(priority, int):
                priority = None
        elif body:
            priority = _peek_priority(body)
    return RequestInfo(
        method=method,
        path=bare,
        verb=(
            "watch" if is_watch
            else "batch" if _is_batch(bare, method)
            else _VERBS.get(method, "get")
        ),
        kind=kind,
        namespace=_namespace_of(bare),
        user_agent=(headers or {}).get("user-agent") or "",
        priority=priority,
        is_watch=is_watch,
        items=items,
    )


def classify(info: RequestInfo,
             schemas: tuple[FlowSchema, ...] = DEFAULT_SCHEMAS) -> str:
    """Request descriptor -> priority level name. Route class first
    (exempt and fixed classes bypass the schemas), then watches to the
    watch pool, then the first matching FlowSchema."""
    cls = route_class(info.path)
    if cls == LEVEL_EXEMPT:
        return LEVEL_EXEMPT
    if info.is_watch:
        return LEVEL_WATCH
    if cls != "workload":
        return cls
    for schema in schemas:
        if schema.matches(info):
            return schema.level
    return LEVEL_LOW
