"""Flow controller: seat accounting, shuffle-sharded queues, shedding.

The runtime half of the API Priority & Fairness analog (config lives in
:mod:`jobset_tpu.flow.config`). One :class:`FlowController` sits in
front of ``ControllerServer._route``:

* ``admit()`` classifies the arrival and either grants a seat
  (``execute``), parks it in its level's shuffle-sharded bounded FIFO
  queue until a seat frees or the wait budget expires, sheds it
  (``reject`` -> the server answers ``429 + Retry-After`` BEFORE any
  routing, so a shed request can never have side effects), or — for
  watch long-polls past the watch seat pool — returns ``busy`` (the
  server answers an immediate partial batch with a retry hint instead
  of parking a handler thread).
* ``release()`` frees the seat and hands it to the longest-waiting
  parked request across the level's queues (global FIFO by arrival).

Determinism: queue selection is *hash*-shuffle-sharded from
``(seed, flow_key)`` — a pure function, no RNG state — and the bounded
decision log records only (arrival seq, level, flow, decision, reason),
never wall-clock values, so a seeded storm driven sequentially (see
``chaos/scenarios.py::thundering_herd``) produces byte-identical logs.
Time enters only through the injectable ``now`` callable (monotonic by
default, a virtual clock in tests) and the real ``Event.wait`` used by
the blocking path.
"""

from __future__ import annotations

import hashlib
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Optional

from .config import (
    DEFAULT_LEVELS,
    DEFAULT_SCHEMAS,
    FlowSchema,
    PriorityLevel,
    RequestInfo,
    classify,
)

# Ticket decisions.
EXECUTE = "execute"
QUEUED = "queued"
REJECT = "reject"
BUSY = "busy"

# Shed reasons (the `reason` label of jobset_flow_rejected_total).
REASON_QUEUE_FULL = "queue_full"   # the flow's sharded queue is at bound
REASON_TIMEOUT = "timeout"         # parked past the level's wait budget
REASON_SATURATED = "saturated"     # level has no queues and no free seat
REASON_WATCH_BUSY = "watch_busy"   # watch pool full: answered 200 + hint,
#                                    counted here for visibility, not a 429


@dataclass
class _Waiter:
    """One parked request (owned by the controller lock)."""

    seq: int
    enqueued_at: float
    queue_index: int
    width: int = 1  # seats this request occupies (batched verbs > 1)
    event: threading.Event = field(default_factory=threading.Event)
    granted: bool = False


@dataclass
class FlowTicket:
    """The admission outcome handed back to the server."""

    level: str
    decision: str
    flow_key: str = ""
    reason: str = ""
    retry_after_s: float = 1.0
    queue_wait_s: float = 0.0
    waiter: Optional[_Waiter] = None
    # Seat width (docs/protocol.md "Batched verbs"): a batched request
    # occupies `items` seats for its whole execution, so one 64-item
    # batchCreate weighs on the fairness budget like 64 single writes.
    width: int = 1


class _LevelState:
    def __init__(self, level: PriorityLevel):
        self.level = level
        self.executing = 0
        self.queues: list[deque] = [deque() for _ in range(level.queues)]

    def queued(self) -> int:
        return sum(len(q) for q in self.queues)


class FlowController:
    """Thread-safe admission gate over a set of priority levels."""

    MAX_LOG = 100_000  # bounded, large enough to diff a whole storm

    def __init__(
        self,
        levels: Optional[tuple[PriorityLevel, ...]] = None,
        schemas: Optional[tuple[FlowSchema, ...]] = None,
        seed: int = 0,
        now: Callable[[], float] = time.monotonic,
    ):
        self.seed = seed
        self.schemas = tuple(schemas) if schemas is not None else DEFAULT_SCHEMAS
        self._now = now
        self._lock = threading.Lock()
        self._levels = {
            lv.name: _LevelState(lv) for lv in (levels or DEFAULT_LEVELS)
        }
        self._arrivals = 0  # guarded-by: _lock
        self._rejected: dict[tuple[str, str], int] = {}  # guarded-by: _lock
        self.log: list[dict] = []  # guarded-by: _lock

    # -- admission --------------------------------------------------------

    def admit(self, info: RequestInfo, block: bool = True) -> FlowTicket:
        """One arrival. Returns an ``execute``/``reject``/``busy`` ticket
        (``queued`` only with ``block=False`` — resolve with
        :meth:`resolve` after granting or expiring it; tests drive this
        path deterministically on a virtual clock)."""
        level_name = classify(info, self.schemas)
        flow_key = info.flow_key
        ticket = self._admit_locked_phase(
            level_name, flow_key, info.is_watch,
            width=max(1, getattr(info, "items", 1)),
        )
        self._account(ticket)
        if ticket.decision == QUEUED and block:
            budget = self._levels[level_name].level.queue_wait_s
            ticket.waiter.event.wait(budget)
            ticket = self.resolve(ticket)
        return ticket

    def _admit_locked_phase(self, level_name: str, flow_key: str,
                            is_watch: bool, width: int = 1) -> FlowTicket:
        with self._lock:
            self._arrivals += 1
            seq = self._arrivals
            state = self._levels[level_name]
            lv = state.level
            if lv.seats <= 0 or state.executing < lv.seats:
                # Width accounting (APF's seat-width idiom): admission
                # needs one free seat, execution occupies `width` —
                # a wide batch may overshoot the level bound for its own
                # duration, but everything arriving behind it waits until
                # the batch's seats free, so sustained batch load is
                # metered exactly like the equivalent single writes.
                state.executing += width
                self._log_locked(seq, level_name, flow_key, EXECUTE, "")
                return FlowTicket(level=level_name, decision=EXECUTE,
                                  flow_key=flow_key,
                                  retry_after_s=lv.retry_after_s,
                                  width=width)
            if is_watch:
                # Watch pool saturated: the server answers an immediate
                # partial batch + retry hint; no seat, no queue, no 429.
                self._log_locked(seq, level_name, flow_key, BUSY,
                                 REASON_WATCH_BUSY)
                self._count_rejected_locked(level_name, REASON_WATCH_BUSY)
                return FlowTicket(level=level_name, decision=BUSY,
                                  flow_key=flow_key,
                                  reason=REASON_WATCH_BUSY,
                                  retry_after_s=lv.retry_after_s)
            if lv.queues <= 0:
                self._log_locked(seq, level_name, flow_key, REJECT,
                                 REASON_SATURATED)
                self._count_rejected_locked(level_name, REASON_SATURATED)
                return FlowTicket(level=level_name, decision=REJECT,
                                  flow_key=flow_key,
                                  reason=REASON_SATURATED,
                                  retry_after_s=lv.retry_after_s)
            qi = self._shard(lv, state, flow_key)
            if len(state.queues[qi]) >= lv.queue_length:
                self._log_locked(seq, level_name, flow_key, REJECT,
                                 REASON_QUEUE_FULL)
                self._count_rejected_locked(level_name, REASON_QUEUE_FULL)
                return FlowTicket(level=level_name, decision=REJECT,
                                  flow_key=flow_key,
                                  reason=REASON_QUEUE_FULL,
                                  retry_after_s=lv.retry_after_s)
            waiter = _Waiter(seq=seq, enqueued_at=self._now(),
                             queue_index=qi, width=width)
            state.queues[qi].append(waiter)
            return FlowTicket(level=level_name, decision=QUEUED,
                              flow_key=flow_key,
                              retry_after_s=lv.retry_after_s,
                              waiter=waiter, width=width)

    def resolve(self, ticket: FlowTicket) -> FlowTicket:
        """Finish a ``queued`` ticket: granted waiters become ``execute``
        (their seat was already taken by the granting release), anything
        else is shed as a ``timeout``. The blocking admit path calls this
        after ``Event.wait``; deterministic tests call it directly after
        advancing the virtual clock or releasing a held seat."""
        waiter = ticket.waiter
        with self._lock:
            state = self._levels[ticket.level]
            wait_s = max(0.0, self._now() - waiter.enqueued_at)
            ticket.queue_wait_s = wait_s
            if waiter.granted:
                # release() granted under this same lock and already
                # dequeued the waiter; the seat is ours.
                ticket.decision = EXECUTE
            else:
                state.queues[waiter.queue_index].remove(waiter)
                ticket.decision = REJECT
                ticket.reason = REASON_TIMEOUT
                self._count_rejected_locked(ticket.level, REASON_TIMEOUT)
            self._log_locked(waiter.seq, ticket.level, ticket.flow_key,
                             ticket.decision, ticket.reason)
        self._account(ticket, queue_wait=True)
        return ticket

    def release(self, ticket: FlowTicket) -> None:
        """Free an executing ticket's seat and grant it to the longest-
        waiting parked request of the level (global FIFO across the
        sharded queues). ``reject``/``busy`` tickets hold nothing."""
        if ticket is None or ticket.decision != EXECUTE:
            return
        grants: list[_Waiter] = []
        with self._lock:
            state = self._levels[ticket.level]
            state.executing -= ticket.width
            lv = state.level
            # A wide release may free several seats: keep granting in
            # global FIFO order while seats remain (each grant occupies
            # its own width, so a wide waiter closes the window).
            while lv.seats > 0 and state.executing < lv.seats:
                grant = self._next_waiter_locked(state)
                if grant is None:
                    break
                grant.granted = True
                state.executing += grant.width
                grants.append(grant)
            inflight = state.executing
        from ..core import metrics

        metrics.flow_inflight.set(inflight, ticket.level)
        for grant in grants:
            grant.event.set()

    def hold(self, level: str, n: int) -> list[FlowTicket]:
        """Acquire `n` seats of `level` directly (test/scenario hook:
        simulates long-running in-flight requests so a sequential driver
        can exercise saturation deterministically). Release each ticket
        to free the seats."""
        out = []
        with self._lock:
            state = self._levels[level]
            for _ in range(n):
                state.executing += 1
                out.append(FlowTicket(level=level, decision=EXECUTE,
                                      flow_key="hold"))
            inflight = state.executing
        from ..core import metrics

        metrics.flow_inflight.set(inflight, level)
        return out

    # -- internals --------------------------------------------------------

    def _shard(self, lv: PriorityLevel, state: _LevelState,
               flow_key: str) -> int:
        """Shuffle sharding: (seed, flow_key) hashes to a hand of
        candidate queues; the flow enqueues on the least-loaded of its
        hand. Pure function of (seed, flow, occupancy) — deterministic
        under a seeded sequential driver, and a single hot flow cannot
        occupy queues outside its hand."""
        n = lv.queues
        hand: list[int] = []
        i = 0
        while len(hand) < min(lv.hand_size, n):
            digest = hashlib.blake2b(
                f"{self.seed}/{flow_key}/{i}".encode(), digest_size=8
            ).digest()
            candidate = int.from_bytes(digest, "big") % n
            if candidate not in hand:
                hand.append(candidate)
            i += 1
        return min(hand, key=lambda qi: (len(state.queues[qi]),
                                         hand.index(qi)))

    @staticmethod
    def _next_waiter_locked(state: _LevelState) -> Optional[_Waiter]:
        best: Optional[deque] = None
        for q in state.queues:
            if q and (best is None or q[0].seq < best[0].seq):
                best = q
        return best.popleft() if best is not None else None

    def _count_rejected_locked(self, level: str, reason: str) -> None:
        key = (level, reason)
        self._rejected[key] = self._rejected.get(key, 0) + 1

    def _log_locked(self, seq: int, level: str, flow: str, decision: str,
                    reason: str) -> None:
        if len(self.log) < self.MAX_LOG:
            self.log.append({
                "seq": seq, "level": level, "flow": flow,
                "decision": decision, "reason": reason,
            })

    def _account(self, ticket: FlowTicket, queue_wait: bool = False) -> None:
        """Metrics, outside the controller lock (the handler pool must
        not serialize on metric locks)."""
        from ..core import metrics

        if ticket.decision == EXECUTE:
            with self._lock:
                inflight = self._levels[ticket.level].executing
            metrics.flow_inflight.set(inflight, ticket.level)
        elif ticket.decision in (REJECT, BUSY):
            metrics.flow_rejected_total.inc(ticket.level, ticket.reason)
        if queue_wait:
            metrics.flow_queue_wait_seconds.observe(ticket.queue_wait_s)

    # -- introspection ----------------------------------------------------

    def log_snapshot(self) -> list[dict]:
        with self._lock:
            return [dict(e) for e in self.log]

    def rejected_total(self) -> int:
        with self._lock:
            return sum(
                n for (_, reason), n in self._rejected.items()
                if reason != REASON_WATCH_BUSY
            )

    def snapshot(self) -> dict:
        """Per-level stats for /debug/health's `flow` component."""
        with self._lock:
            levels = {
                name: {
                    "seats": state.level.seats,
                    "executing": state.executing,
                    "queued": state.queued(),
                    "queueWaitBudgetS": state.level.queue_wait_s,
                }
                for name, state in sorted(self._levels.items())
            }
            rejected: dict[str, dict[str, int]] = {}
            for (level, reason), n in sorted(self._rejected.items()):
                rejected.setdefault(level, {})[reason] = n
            arrivals = self._arrivals
        return {"levels": levels, "rejected": rejected,
                "arrivals": arrivals}
